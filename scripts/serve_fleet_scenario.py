#!/usr/bin/env python
"""End-to-end replica-fleet scenario: the serve/fleet evidence producer.

Drives the REAL stack — ``python -m simclr_pytorch_distributed_tpu.serve.
fleet`` replica subprocesses under the REAL :class:`ReplicaFleetSupervisor`
(supervise/replica_fleet.py), scraped over live HTTP — through the fleet's
headline claims, and commits what happened as
``docs/evidence/serve_fleet_r17.json`` (``scripts/ratchet.py`` re-verifies
the artifact with the pure ``serve_fleet_gate_record``):

1. **spawn** — the supervisor raises the fleet to ``min_replicas=2`` from
   scraped ``/metrics`` alone; both replicas serve ``/embed``;
2. **kill -> restart** — a replica is SIGKILLed; the next supervision tick
   classifies it dead and relaunches it on the SAME port within the
   restart budget; the replica serves again;
3. **hot-swap under load** — ``/models/promote`` lands while client threads
   hammer ``/embed``; the swap drains (old version retired, new serving)
   with ZERO failed requests across the window;
4. **retrieval** — served embeddings answer ``/neighbors`` with the query
   image itself as top-1 at cosine ~1.0.

Checkpoints are built in-process (tiny resnet10 @ 8x8 — the serve test
geometry); replicas inherit ``JAX_PLATFORMS=cpu`` and the repo compile
cache so startup is dominated by imports, not compiles.

Usage:
    python scripts/serve_fleet_scenario.py \
        --json docs/evidence/serve_fleet_r17.json
"""

import argparse
import base64
import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from simclr_pytorch_distributed_tpu.serve.fleet.registry import (  # noqa: E402
    ModelRegistry,
)
from simclr_pytorch_distributed_tpu.supervise.replica import (  # noqa: E402
    ReplicaPolicy,
)
from simclr_pytorch_distributed_tpu.supervise.replica_fleet import (  # noqa: E402
    ReplicaFleetConfig,
    ReplicaFleetSupervisor,
)

SCHEMA = "serve_fleet/v1"
SIZE = 8


def build_checkpoint(path, seed):
    """A tiny real checkpoint the fleet CLI can serve (the
    tests/test_serve_engine.py from_checkpoint recipe)."""
    import jax
    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.utils.checkpoint import (
        MODEL_LAYOUT_VERSION,
        _save_tree,
        _write_meta,
    )

    model = SupConResNet(model_name="resnet10")
    v = model.init(
        jax.random.key(seed), jnp.zeros((2, SIZE, SIZE, 3)), train=False
    )
    _save_tree(
        os.path.join(path, "model"),
        {"params": v["params"], "batch_stats": v["batch_stats"]},
    )
    _write_meta(path, {
        "epoch": 1, "model_layout": MODEL_LAYOUT_VERSION,
        "config": {"dataset": "cifar10"},
    })
    return path


def post(port, path, obj, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def get(port, path, timeout=10):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=timeout
    ) as r:
        return r.status, json.loads(r.read())


def embed_req(port, images, model=None, tenant="", timeout=60):
    body = {
        "images_b64": base64.b64encode(np.ascontiguousarray(images).tobytes()).decode(),
        "shape": list(images.shape),
    }
    if model:
        body["model"] = model
    if tenant:
        body["tenant"] = tenant
    return post(port, "/embed", body, timeout=timeout)


def wait_until(predicate, timeout_s, what, poll_s=0.5):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(poll_s)
    raise RuntimeError(f"timed out waiting for {what}")


def serving_ok(port):
    try:
        return get(port, "/healthz", timeout=2)[0] == 200
    except (urllib.error.URLError, OSError):
        return False


def load_window(port, rng, stop, counters, lock):
    """One client thread: hammer /embed until told to stop, count fates."""
    while not stop.is_set():
        images = rng.integers(0, 256, size=(2, SIZE, SIZE, 3), dtype=np.uint8)
        try:
            status, _ = embed_req(port, images, tenant="load")
            with lock:
                counters["ok" if status == 200 else "other"] += 1
        except urllib.error.HTTPError as e:
            with lock:
                counters[f"http_{e.code}"] = counters.get(f"http_{e.code}", 0) + 1
        except (urllib.error.URLError, OSError):
            with lock:
                counters["transport"] = counters.get("transport", 0) + 1


def run_scenario(workdir):
    ck1 = build_checkpoint(os.path.join(workdir, "ckpt_v1"), seed=0)
    ck2 = build_checkpoint(os.path.join(workdir, "ckpt_v2"), seed=1)

    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    config = ReplicaFleetConfig(
        command=[
            sys.executable, "-m", "simclr_pytorch_distributed_tpu.serve.fleet",
            "--ckpt", ck1, "--name", "prod", "--host", "127.0.0.1",
            "--port", "{port}", "--img_size", str(SIZE), "--buckets", "2,8",
            "--max_wait_ms", "2",
        ],
        min_replicas=2, max_replicas=3, grace_s=10.0,
    )
    policy = ReplicaPolicy(2, 3, startup_grace_s=180.0, max_restarts=2)
    sup = ReplicaFleetSupervisor(config, policy, env=env)
    out = {"phases": {}}
    try:
        # phase 1: the supervisor raises the fleet to its floor
        sup.step()
        sup.step()
        replicas = sup.replicas()
        assert len(replicas) == 2, replicas
        ports = {rid: r["port"] for rid, r in replicas.items()}
        wait_until(
            lambda: all(serving_ok(p) for p in ports.values()), 240,
            "both replicas serving /healthz",
        )
        # ...and sees them through /metrics, not just /healthz
        wait_until(
            lambda: all(o.metrics is not None for o in sup.observe()), 60,
            "both replicas scrapeable",
        )
        rng = np.random.default_rng(0)
        warm = {}
        for rid, port in ports.items():
            status, r = embed_req(port, rng.integers(0, 256, size=(2, SIZE, SIZE, 3), dtype=np.uint8))
            warm[str(rid)] = {"status": status, "dim": r["dim"], "model": r["model"]}
            assert status == 200 and r["model"] == "prod"
        out["phases"]["spawn"] = {
            "replicas": {str(k): v for k, v in sup.replicas().items()},
            "decisions": sup.decisions(),
            "warm_embed": warm,
            "ok": True,
        }

        # phase 2: SIGKILL replica 0; the next tick restarts it on its port
        victim = min(ports)
        victim_pid = sup.replicas()[victim]["pid"]
        os.kill(victim_pid, signal.SIGKILL)
        wait_until(
            lambda: sup.replicas()[victim]["alive"] is False, 30,
            "the kill to register",
        )
        decisions = sup.step()
        restart = [d for d in decisions if d["action"] == "restart_replica"]
        assert restart and restart[0]["replica"] == victim, decisions
        assert restart[0]["port"] == ports[victim]
        wait_until(
            lambda: serving_ok(ports[victim]), 240,
            "the restarted replica to serve again",
        )
        status, _ = embed_req(
            ports[victim],
            rng.integers(0, 256, size=(2, SIZE, SIZE, 3), dtype=np.uint8),
        )
        out["phases"]["restart"] = {
            "killed_pid": victim_pid,
            "replica": victim,
            "port": ports[victim],
            "decisions": decisions,
            "served_after_restart": status == 200,
            "restarts": sup.replicas()[victim]["restarts"],
            "ok": status == 200,
        }

        # phase 3: hot-swap promote on the OTHER replica while client
        # threads hammer it — zero failures across the swap window
        target = max(ports)
        port = ports[target]
        counters = {"ok": 0, "other": 0}
        lock = threading.Lock()
        stop = threading.Event()
        threads = [
            threading.Thread(
                target=load_window,
                args=(port, np.random.default_rng(100 + i), stop, counters, lock),
                daemon=True,
            )
            for i in range(3)
        ]
        for t in threads:
            t.start()
        wait_until(lambda: counters["ok"] >= 10, 120, "load to flow")
        status, promoted = post(
            port, "/models/promote", {"model": "prod", "ckpt": ck2},
            timeout=240,
        )
        assert status == 200 and promoted["version"] == 2, promoted
        # keep the load up through the drain window, then stop
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)

        def versions():
            return {
                v["version"]: v["state"]
                for v in get(port, "/models")[1]["models"]["prod"]["versions"]
            }

        wait_until(
            lambda: versions().get(1) == "retired", 60,
            "the old version to drain and retire",
        )
        vstates = versions()
        failures = {k: v for k, v in counters.items() if k != "ok" and v}
        out["phases"]["promote"] = {
            "response": promoted,
            "embed_ok": counters["ok"],
            "embed_failures": failures,
            "versions": {str(k): v for k, v in vstates.items()},
            "drained": vstates.get(1) == "retired" and vstates.get(2) == "serving",
            "ok": not failures and vstates.get(2) == "serving",
        }

        # phase 4: retrieval — the corpus answers /neighbors with the query
        # itself as top-1 at cosine ~1.0
        corpus = rng.integers(0, 256, size=(4, SIZE, SIZE, 3), dtype=np.uint8)
        embed_req(port, corpus)
        query = corpus[1:2]
        status, r = post(port, "/neighbors", {
            "images_b64": base64.b64encode(query.tobytes()).decode(),
            "shape": list(query.shape), "k": 2,
        })
        top = r["neighbors"][0][0]
        self_id = ModelRegistry.content_id(query[0])
        out["phases"]["neighbors"] = {
            "status": status,
            "top1_id": top["id"],
            "expected_id": self_id,
            "top1_score": top["score"],
            "k": r["k"],
            "self_top1": top["id"] == self_id and top["score"] > 0.999,
            "ok": top["id"] == self_id and top["score"] > 0.999,
        }
        out["ok"] = all(p["ok"] for p in out["phases"].values())
        out["gave_up"] = sup.gave_up()
        out["decisions"] = sup.decisions()
        return out
    finally:
        sup.stop_all()


def build_output(phases_result):
    """Pure artifact assembly (the supervisor_matrix convention): what the
    ratchet gate re-verifies, stamped with the pinned schema."""
    return {
        "metric": "serve_fleet_scenario",
        "schema": SCHEMA,
        "replica_command": "python -m simclr_pytorch_distributed_tpu.serve.fleet",
        "min_replicas": 2,
        "img_size": SIZE,
        **phases_result,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--workdir",
        default=os.path.join(REPO, "work_space", "serve_fleet_scenario"),
    )
    ap.add_argument(
        "--json",
        default=os.path.join(REPO, "docs", "evidence", "serve_fleet_r17.json"),
    )
    args = ap.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    # fresh-artifact convention (scripts/ratchet.py): a failed producer
    # must never leave a stale green artifact for the gate to re-verify
    if args.json and os.path.exists(args.json):
        os.remove(args.json)
    result = run_scenario(args.workdir)
    out = build_output(result)
    print(json.dumps({"metric": out["metric"], "ok": out["ok"]}), flush=True)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    sys.exit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
