#!/usr/bin/env python
"""Recipe-subsystem evidence: supcon-refactor bit-identity + per-recipe
online-probe accuracy (docs/evidence/recipes_r12.json; the ``recipes``
config in scripts/ratchet.py's default gate list).

Two claims, both through the REAL pretrain driver:

1. **Bit-identity** — ``--recipe supcon`` through the recipe interface
   produces BITWISE-identical params to the pre-refactor inline update
   (``make_fused_update(recipe=None)``, the retained legacy path) over a
   multi-epoch run, under BOTH host and device data placement. This is the
   contract that lets every committed accuracy ratchet carry over the
   refactor unchanged (docs/PARITY.md).
2. **Per-recipe learning** — each recipe (supcon, byol, simsiam, vicreg,
   and the simclr+--moco_queue arm) trains with the online probe + health
   stream on; the probe's best windowed top-1 (read back from the run's
   own events.jsonl via scripts/health_report.py) must clear a
   CPU-calibrated bar over the 10% random baseline, with ZERO collapse
   alarms. The bars live in scripts/ratchet.py (RECIPE_PROBE_CPU_BARS) and
   bind on CPU only — elsewhere the gate pass-skips with the reason on
   record (the bench-gate convention).

Usage:
    python scripts/recipes_eval.py --json docs/evidence/recipes_r12.json
    python scripts/recipes_eval.py --smoke --json out.json   # ratchet gate
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SCHEMA = "recipes_eval/v1"

# the probe arms: (arm name, config overrides). simclr_queue is the
# MoCo-style ring on the simclr recipe — the queue must not break learning.
PROBE_ARMS = (
    ("supcon", dict(recipe="supcon")),
    ("byol", dict(recipe="byol")),
    ("simsiam", dict(recipe="simsiam")),
    ("vicreg", dict(recipe="vicreg")),
    ("simclr_queue", dict(recipe="simclr", moco_queue=256)),
)


def _cfg(args, trial, **over):
    from simclr_pytorch_distributed_tpu import config as config_lib

    base = dict(
        model="resnet10", dataset="synthetic", batch_size=64,
        learning_rate=0.05, cosine=True, temp=0.5, method="SimCLR",
        epochs=args.epochs, save_freq=max(1, args.epochs),
        print_freq=5, size=args.size, seed=args.seed,
        workdir=args.workdir, trial=trial, telemetry="sync",
        flight_recorder="on", predictor_hidden=128,
    )
    base.update(over)
    cfg = config_lib.SupConConfig(**base)
    return config_lib.finalize_supcon(cfg)


def _run(cfg):
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    return supcon_driver.run(cfg)


def _trees_bitwise_equal(a, b):
    import jax
    import numpy as np

    fa = jax.tree.leaves(jax.device_get(a))
    fb = jax.tree.leaves(jax.device_get(b))
    if len(fa) != len(fb):
        return False
    return all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb)
    )


def bit_identity_check(args):
    """``--recipe supcon`` (interface) vs ``recipe=None`` (the pre-refactor
    inline step) through the REAL driver, per data placement. The legacy
    arm is forced by pinning the driver's update builder — everything else
    (telemetry keys, slots, checkpoints) is identical by the slot-free
    recipe contract."""
    from simclr_pytorch_distributed_tpu.train import supcon as supcon_driver

    placements = ("host", "device")
    record = {"epochs": args.epochs, "placements": {}}
    orig_mfu = supcon_driver.make_fused_update
    for placement in placements:
        states = {}
        for arm in ("recipe", "legacy"):
            if arm == "legacy":
                def legacy_mfu(*a, **kw):
                    kw["recipe"] = None
                    return orig_mfu(*a, **kw)

                supcon_driver.make_fused_update = legacy_mfu
            try:
                cfg = _cfg(
                    args, trial=f"{args.trial}_bit_{placement}_{arm}",
                    recipe="supcon", method="SupCon",
                    data_placement=placement,
                )
                states[arm] = _run(cfg)
            finally:
                supcon_driver.make_fused_update = orig_mfu
        identical = (
            _trees_bitwise_equal(states["recipe"].params,
                                 states["legacy"].params)
            and _trees_bitwise_equal(states["recipe"].batch_stats,
                                     states["legacy"].batch_stats)
            and _trees_bitwise_equal(states["recipe"].opt_state,
                                     states["legacy"].opt_state)
        )
        record["placements"][placement] = bool(identical)
        record["steps"] = int(states["recipe"].step)
    record["ok"] = all(record["placements"].values())
    return record


def probe_arm(args, name, over):
    """One recipe pretrain with the online probe + health stream on; the
    probe trajectory is read back from the run's OWN events.jsonl (the
    durable health stream), not from driver internals."""
    import scripts.health_report as hr

    cfg = _cfg(
        args, trial=f"{args.trial}_{name}",
        online_probe="on", health_freq=2, health_policy="warn", **over,
    )
    _run(cfg)
    events = hr.load_events(os.path.join(cfg.save_folder, "events.jsonl"))
    rep = hr.build_report(events)
    probe = rep["probe"] or {}
    return {
        "recipe": over["recipe"],
        "moco_queue": over.get("moco_queue", 0),
        "probe_best_top1": probe.get("best_top1"),
        "probe_first_top1": probe.get("first_top1"),
        "probe_last_top1": probe.get("last_top1"),
        "windows": probe.get("windows"),
        "alarms": len(rep["alarms"]),
        "consistency_ok": rep["consistency"]["ok"],
        "thresholds": rep["thresholds"],
    }


def build_output(device, smoke, config, bit_identity, recipes):
    """The committed artifact (pure; schema pinned by tests)."""
    return {
        "schema": SCHEMA,
        "device": device,
        "smoke": bool(smoke),
        "config": config,
        "bit_identity": bit_identity,
        "recipes": recipes,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default="", help="write the artifact here")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny ratchet-gate config (size 8, 1 epoch)")
    ap.add_argument("--epochs", type=int, default=None,
                    help="pretrain epochs per arm (default: 2; smoke: 1)")
    ap.add_argument("--size", type=int, default=None,
                    help="image side (default: 16; smoke: 8)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--trial", default="recipes_eval")
    ap.add_argument("--workdir",
                    default=os.path.join(REPO, "work_space", "recipes_eval"))
    args = ap.parse_args(argv)
    if args.epochs is None:
        args.epochs = 1 if args.smoke else 2
    if args.size is None:
        args.size = 8 if args.smoke else 16

    import jax

    bit = bit_identity_check(args)
    print(json.dumps({"bit_identity": bit}), flush=True)
    recipes = {}
    for name, over in PROBE_ARMS:
        recipes[name] = probe_arm(args, name, over)
        print(json.dumps({name: recipes[name]}), flush=True)

    out = build_output(
        jax.default_backend(), args.smoke,
        {"epochs": args.epochs, "size": args.size, "seed": args.seed,
         "batch_size": 64, "model": "resnet10"},
        bit, recipes,
    )
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.json}")
    ok = bit["ok"] and all(
        r["consistency_ok"] and not r["alarms"] for r in recipes.values()
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
