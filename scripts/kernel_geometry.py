#!/usr/bin/env python
"""Fused-sharded loss kernels vs the dense path at PER-DEVICE pod geometry.

Round-4 verdict weak #2: ``resolve_loss_impl('auto')`` picks the sharded
fused kernel on any multi-device TPU mesh, but its win was only ever measured
at m=512 anchor rows (single chip, full batch). On the v5e-8 north-star
config each device owns m = 2*256/8 = **64** anchor rows x 512 contrast
columns — an 8x-skinnier Pallas grid. This script times, on the real chip:

- **fused**: the exact rectangular kernels the sharded path runs per device
  (``ops/pallas_loss.py _fwd_call`` + ``_bwd_call`` — local anchor rows vs
  the all-gathered contrast matrix, logits tiles VMEM-only, backward from
  the gathered O(N) lse/cnt vectors);
- **dense**: ``jax.value_and_grad`` of the same per-device slice computed the
  dense way (the [m, N] logits block + softmax temporaries materialized, XLA
  saving residuals for the backward) — what GSPMD hands each device under
  ``loss_impl='dense'``.

Both paths exclude the feature all-gather (identical O(N*D) cost in either
mode, so it cancels in the comparison). Honest-sync methodology per
docs/PERF.md: every timed window chains each iteration on the previous
result (no async pipelining of independent dispatches) and ends with a
host readback of a computed scalar; median of 5 windows.

Usage:  python scripts/kernel_geometry.py [--rows 64 128 256 512] [--json OUT]
"""

import argparse
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from _honest_timing import time_per_iter  # noqa: E402
from simclr_pytorch_distributed_tpu.ops.pallas_loss import (  # noqa: E402
    _bwd_call,
    _fwd_call,
    _pick_block,
)

N = 512          # global view rows: batch 256 x 2 views (the recipe config)
D = 128          # feat_dim
TEMP, BASE_TEMP = 0.5, 0.07
NEG = -1e30


def _make_inputs(m, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.standard_normal((N, D)).astype(np.float32)
    feats /= np.linalg.norm(feats, axis=1, keepdims=True)
    ids = np.tile(np.arange(N // 2, dtype=np.int32), 2)  # SimCLR sample ids
    return (
        jnp.asarray(feats[:m]), jnp.asarray(feats),
        jnp.asarray(ids[:m]), jnp.asarray(ids),
        jnp.arange(m, dtype=jnp.int32), jnp.arange(N, dtype=jnp.int32),
    )


def _fused_core(m):
    bm, bn = _pick_block(m, 256), _pick_block(N, 512)
    coeff = (TEMP / BASE_TEMP) / N
    interpret = jax.default_backend() != "tpu"

    def step(i, frow, fcol, idr, idc, grow, gcol, lse_all, cnt_all):
        loss_rows, lse, cnt = _fwd_call(
            frow, fcol, idr, idc, grow, gcol,
            TEMP, BASE_TEMP, interpret, bm, bn,
        )
        d = _bwd_call(
            frow, fcol, idr, idc, grow, gcol,
            lse[:, 0], lse_all, cnt[:, 0], cnt_all,
            TEMP, coeff, interpret, bm, bn,
        )
        # the 1e-20 term keeps the backward alive in the chained loop below
        # without perturbing the loss (not foldable: d is a runtime value)
        return jnp.mean(loss_rows) + jnp.sum(jnp.abs(d)) * 1e-20

    return step


def _dense_core(m):
    def local_loss(frow, fcol, idr, idc, grow, gcol):
        logits = (frow @ fcol.T) / TEMP                    # [m, N] in HBM
        self_mask = grow[:, None] == gcol[None, :]
        pos = ((idr[:, None] == idc[None, :]) & ~self_mask).astype(jnp.float32)
        masked = jnp.where(self_mask, NEG, logits)
        # detached row max, as the reference subtracts (losses.py:68-69)
        row_max = jax.lax.stop_gradient(jnp.max(masked, axis=1, keepdims=True))
        shifted = masked - row_max
        log_prob = shifted - jnp.log(
            jnp.sum(jnp.exp(shifted), axis=1, keepdims=True)
        )
        mean_pos = jnp.sum(pos * log_prob, axis=1) / jnp.sum(pos, axis=1)
        return jnp.mean(-(TEMP / BASE_TEMP) * mean_pos)

    grad_fn = jax.value_and_grad(local_loss, argnums=(0, 1))

    def step(i, frow, fcol, idr, idc, grow, gcol, lse_all, cnt_all):
        loss, (dfrow, dfcol) = grad_fn(frow, fcol, idr, idc, grow, gcol)
        return loss + (jnp.sum(jnp.abs(dfrow)) + jnp.sum(jnp.abs(dfcol))) * 1e-20

    return step


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, nargs="+", default=[64, 128, 256, 512],
                    help="anchor rows per device (64 = v5e-8 at batch 256)")
    ap.add_argument("--iters", type=int, default=5000)
    ap.add_argument("--json", default=None, help="also write records here")
    args = ap.parse_args()
    if args.iters < 2:
        ap.error("--iters must be >= 2 (per-iter time divides by iters - 1)")

    records = []
    for m in args.rows:
        frow, fcol, idr, idc, grow, gcol = _make_inputs(m)
        # column-side softmax stats: in the real sharded backward these are
        # the all-gathered residuals; here computed once, outside the window
        _, lse_full, cnt_full = _fwd_call(
            fcol, fcol, idc, idc, gcol, gcol, TEMP, BASE_TEMP,
            jax.default_backend() != "tpu",
            _pick_block(N, 256), _pick_block(N, 512),
        )
        lse_all, cnt_all = lse_full[:, 0], cnt_full[:, 0]
        common = (frow, fcol, idr, idc, grow, gcol, lse_all, cnt_all)

        fused_ms = time_per_iter(_fused_core(m), common, iters=args.iters) * 1e3
        dense_ms = time_per_iter(_dense_core(m), common, iters=args.iters) * 1e3
        rec = {
            "metric": "loss_kernel_fwd_bwd_ms_per_device",
            "anchor_rows": m, "contrast_cols": N, "feat_dim": D,
            "fused_ms": round(fused_ms, 4), "dense_ms": round(dense_ms, 4),
            # None = the dense window was swallowed by dispatch-floor noise
            "fused_over_dense": (
                round(fused_ms / dense_ms, 3) if dense_ms > 0 else None
            ),
            "device": jax.devices()[0].device_kind,
            "note": "per-device kernel work only; all-gather excluded "
                    "(identical in both modes)",
        }
        records.append(rec)
        print(json.dumps(rec), flush=True)

    if args.json:
        with open(args.json, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
