#!/usr/bin/env python
"""Does device-resident data placement remove the per-step H2D from the loop?

docs/PERF.md round 5 measured the production put-then-dispatch driver loop at
64.9-71.0 ms/step against a stable 64.6-65.2 ms resident-batch floor
(``docs/evidence/h2d_overlap_ab_r5.json``): the per-step uint8 transfer costs
a volatile 0-10 ms on the tunneled link. ``--data_placement device``
(data/device_store.py) claims to reach the measured floor by shipping only an
int32 index vector per EPOCH and slicing every batch out of an HBM-resident
shuffled buffer. This script MEASURES that on a CPU proxy instead of assuming
it, and PROVES the placement swap is free (bit-identical batches):

- both arms run the same model/step config; the ``host`` arm is the
  production loop shape (EpochLoader gather -> ``shard_host_batch`` ->
  dispatch), the ``device`` arm is the resident loop (one index upload +
  compiled shuffle-gather per epoch, then dispatch-only);
- on CPU the real H2D is ~free AND dispatch is asynchronous, so a bare
  injected sleep would hide behind the in-flight step — the opposite of the
  measured tunnel, which SERIALIZES transfers against compute (that
  serialization is the whole 0-10 ms/step penalty). The proxy therefore
  models the serialized stream explicitly: before paying the injected
  ``--h2d_delay_ms`` transfer delay, the arm fences the in-flight step
  (``block_until_ready``), so one step costs compute + transfer exactly as
  on the serialized link. The host arm pays that fence+delay once per STEP
  at ``shard_host_batch``; the device arm once per EPOCH at the index
  upload (via the store's injectable ``index_put``, the same hook the
  transfer-count tests instrument) and is otherwise dispatch-only;
- arm order is ABBA within every round after one full discarded warm arm of
  EACH kind (two compiled programs — compile/settling must land on neither
  measured arm), and the honest-sync rule holds: every timed arm ends with a
  host readback of a COMPUTED loss scalar, which cannot exist until the
  steps actually ran;
- before any timing, an equivalence pass byte-compares every step of two
  device epochs (including a mid-epoch slice) against the host loader —
  ``equivalence_ok`` in the artifact is the bit-identity contract.

Expectation: host_ms - device_ms ~= delay * (1 - 1/steps_per_epoch) (the
device arm still pays one index-upload delay per epoch). The committed
artifact is docs/evidence/resident_ab_r7.json; the chip expectation derived
from it lives in docs/PERF.md ("Device-resident data pipeline").

Usage: python scripts/resident_ab.py [--smoke] [--h2d_delay_ms N] [--json OUT]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.data import device_store  # noqa: E402
from simclr_pytorch_distributed_tpu.data.pipeline import EpochLoader  # noqa: E402
from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: E402
    create_mesh,
    shard_host_batch,
)

ARM_ORDER = ("host", "device", "device", "host")  # ABBA within every round


def build_output(device, h2d_delay_ms, steps_per_epoch, epochs_per_arm,
                 rounds_records, equivalence):
    """Assemble the committed-artifact JSON from per-round arm timings.

    ``rounds_records``: one dict per round, ``{"host": [ms_per_step, ...],
    "device": [...]}`` — two measurements per arm per round (the ABBA
    order). Pure so tests pin the schema without running the measurement.
    """
    all_host = [v for r in rounds_records for v in r["host"]]
    all_device = [v for r in rounds_records for v in r["device"]]
    host_ms = statistics.median(all_host)
    device_ms = statistics.median(all_device)
    return {
        "metric": "resident_ab_ms_per_step",
        "h2d_delay_ms": h2d_delay_ms,
        "steps_per_epoch": steps_per_epoch,
        "epochs_per_arm": epochs_per_arm,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "runs": rounds_records,
        "equivalence": equivalence,
        "summary": {
            "host_ms_per_step": round(host_ms, 2),
            "device_ms_per_step": round(device_ms, 2),
            "transfer_removed_ms_per_step": round(host_ms - device_ms, 2),
            "speedup": round(host_ms / device_ms, 3) if device_ms > 0 else None,
        },
        "device": device,
        "note": (
            "paired CPU-proxy A/B: host arm = production per-step "
            "gather+device_put loop, device arm = HBM-resident epoch buffer "
            "(one index upload/epoch); the injected h2d delay models the "
            "SERIALIZED tunnel link (fence in-flight step, then pay the "
            "delay — PERF.md round-5 measured that serialization) and is "
            "paid per step (host) vs per epoch (device); each arm ends "
            "with a computed-loss readback; equivalence = byte-equal "
            "batches, the bit-identity contract"
        ),
    }


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    def nonneg_float(s):
        v = float(s)
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--h2d_delay_ms", type=nonneg_float, default=None,
                    help="injected per-transfer delay; default 50 ms, 200 ms "
                         "under --smoke (like flush_ab, the injected stall "
                         "must dominate the tiny-model compute so the "
                         "effect clears 1-core timer/contention noise, "
                         "~25 ms/step observed, by a wide margin)")
    ap.add_argument("--steps", type=positive_int, default=None,
                    help="steps per epoch; default 20, 8 under --smoke")
    ap.add_argument("--epochs", type=positive_int, default=None,
                    help="epochs per timed arm; default 3, 2 under --smoke")
    ap.add_argument("--rounds", type=positive_int, default=2,
                    help="ABBA rounds (2 measurements per arm per round)")
    ap.add_argument("--batch", type=positive_int, default=None,
                    help="global batch; default 64, 8 under --smoke")
    ap.add_argument("--size", type=positive_int, default=None,
                    help="default 16, 8 under --smoke")
    ap.add_argument("--model", default="resnet10")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config for tests and the committed-"
                         "artifact run")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke picks the CPU-proxy shape (the injected per-step penalty must
    # clear single-core timer noise by a wide margin) but only for flags the
    # caller left unset — an explicit sweep value is never overridden.
    smoke_defaults = dict(size=8, batch=8, steps=8, epochs=2,
                          h2d_delay_ms=200.0)
    full_defaults = dict(size=16, batch=64, steps=20, epochs=3,
                         h2d_delay_ms=50.0)
    for k, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.train.state import (
        create_train_state,
        make_optimizer,
    )
    from simclr_pytorch_distributed_tpu.train.supcon import make_fused_update
    from simclr_pytorch_distributed_tpu.train.supcon_step import SupConStepConfig

    mesh = create_mesh(devices=jax.devices()[:1])
    delay_s = args.h2d_delay_ms / 1e3

    # dataset sized to exactly steps*batch rows (plus a drop_last remainder
    # so truncation is exercised), same rng recipe as the committed benches
    rng = np.random.default_rng(0)
    n = args.steps * args.batch + args.batch // 2
    images = rng.integers(
        0, 256, size=(n, args.size, args.size, 3), dtype=np.uint8
    )
    labels = rng.integers(0, 10, size=(n,)).astype(np.int32)
    loader = EpochLoader(images, labels, args.batch, base_seed=7)
    assert loader.steps_per_epoch == args.steps

    def delayed_index_put(idx):
        time.sleep(delay_s)  # the device arm's ONE per-epoch transfer
        return jax.device_put(idx)

    store = device_store.DeviceStore(loader, mesh, index_put=delayed_index_put)

    model = SupConResNet(model_name=args.model, head="mlp", feat_dim=128)
    schedule = make_lr_schedule(learning_rate=0.1, epochs=10,
                                steps_per_epoch=args.steps, cosine=True)
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)

    def fresh_state():
        return create_train_state(
            model, tx, jax.random.key(0),
            jnp.zeros((2, args.size, args.size, 3), jnp.float32),
        )

    step_cfg = SupConStepConfig(
        method="SimCLR", temperature=0.5, epochs=10,
        steps_per_epoch=args.steps, grad_div=1.0, loss_impl="dense",
    )
    aug_cfg = AugmentConfig(size=args.size)
    # scalar-mode updates (metric_ring=None): the loop shape under test is
    # the DATA path; telemetry stays out of both arms identically
    update_host = make_fused_update(
        model, tx, schedule, step_cfg, aug_cfg, mesh, fresh_state()
    )
    update_res = make_fused_update(
        model, tx, schedule, step_cfg, aug_cfg, mesh, fresh_state(),
        resident=True,
    )
    base_key = jax.random.key(42)

    # ---- equivalence pass (bit-identity, before any timing) -------------
    checked = 0
    mid = args.steps // 2
    mid_ok = True
    for epoch in (1, 2):
        ep_imgs, ep_labs = store.epoch_buffers(epoch)
        dev_imgs, dev_labs = np.asarray(ep_imgs), np.asarray(ep_labs)
        for s, (h_imgs, h_labs) in enumerate(loader.epoch(epoch)):
            if not (np.array_equal(dev_imgs[s], h_imgs)
                    and np.array_equal(dev_labs[s], h_labs)):
                raise SystemExit(
                    f"placement equivalence BROKEN at epoch {epoch} step {s}"
                )
            checked += 1
        # the mid-epoch resume contract is a slice-offset shift: the buffer
        # row at the resume position IS the loader's batch at that step
        resumed = list(loader.epoch(epoch, start_step=mid))
        mid_ok = mid_ok and np.array_equal(dev_imgs[mid], resumed[0][0])
    equivalence = {
        "equivalence_ok": bool(checked == 2 * args.steps and mid_ok),
        "steps_compared": checked,
        "epochs": 2,
        "mid_epoch_resume_checked": True,
    }
    print(json.dumps({"equivalence": equivalence}), flush=True)

    # ---- timing ---------------------------------------------------------
    epoch_counter = [0]  # monotonically fresh epochs: every arm reshuffles

    def run_arm(mode, state):
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            epoch_counter[0] += 1
            epoch = epoch_counter[0]
            if mode == "device":
                # ONE serialized transfer per epoch (the index upload
                # inside epoch_buffers -> delayed_index_put); fence first —
                # same serialized-stream rule as the host arm's transfers
                jax.block_until_ready(state)
                ep_imgs, ep_labs = store.epoch_buffers(epoch)
                for _ in range(args.steps):
                    state, metrics = update_res(
                        state, ep_imgs, ep_labs, base_key
                    )
            else:
                for h_imgs, h_labs in loader.epoch(epoch):
                    # serialized-link model (module docstring): the tunnel
                    # runs transfer and compute on ONE stream, so the
                    # injected transfer delay cannot start until the
                    # in-flight step retires
                    jax.block_until_ready(state)
                    time.sleep(delay_s)
                    batch = shard_host_batch((h_imgs, h_labs), mesh)
                    state, metrics = update_host(
                        state, batch[0], batch[1], base_key
                    )
        # honest sync: a computed scalar cannot exist until the steps ran
        assert np.isfinite(float(metrics["loss"]))
        dt = time.perf_counter() - t0
        return state, dt * 1e3 / (args.epochs * args.steps)

    # warmup: compile + ONE FULL DISCARDED ARM OF EACH KIND (two compiled
    # programs; allocator/code-cache settling must not land on a timed arm)
    state = fresh_state()
    state, warm_host = run_arm("host", state)
    state, warm_dev = run_arm("device", state)
    print(json.dumps({"warmup_discarded_ms_per_step":
                      {"host": round(warm_host, 2),
                       "device": round(warm_dev, 2)}}), flush=True)

    rounds_records = []
    for rnd in range(args.rounds):
        record = {"host": [], "device": []}
        for mode in ARM_ORDER:
            state, ms = run_arm(mode, state)
            record[mode].append(round(ms, 2))
            print(json.dumps({"round": rnd, "arm": mode,
                              "ms_per_step": round(ms, 2)}), flush=True)
        rounds_records.append(record)

    out = build_output(
        jax.devices()[0].device_kind, args.h2d_delay_ms, args.steps,
        args.epochs, rounds_records, equivalence,
    )
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
