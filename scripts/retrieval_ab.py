#!/usr/bin/env python
"""Is the IVF rung sublinear where it claims to be — and at what recall?

`serve/fleet/retrieval.py` (brute) scores every stored row per query:
exact, O(capacity * dim), fine at the 4096-row default. `serve/fleet/ivf.py`
claims O(nlist * dim + nprobe * avg_list_len * dim) by probing only the
``nprobe`` nearest of its self-trained k-means lists — at the price of a
measurable recall@k against the exact answer. This script MEASURES both
sides of that trade across corpus-size rungs (4k/64k/256k full; tiny under
``--smoke``), the repo's paired-A/B way:

- one corpus per rung, cluster-structured (centers + Gaussian noise — the
  regime served embeddings actually live in; isotropic noise would make
  ANY coarse quantizer look bad and no real corpus look like it), inserted
  into BOTH indexes in the same chunked order with the same content keys;
- **brute-oracle bit-identity before any timing**: the brute rung's
  answers are compared against a frozen numpy restatement of the PR-17
  scoring contract (L2-normalize on insert and query, score = unit-dot,
  argpartition + stable argsort top-k) — ids must match exactly and
  scores must match BITWISE (float32). This is the "brute path retained
  bit-for-bit" contract: it gates the artifact and binds on every device;
- **recall@k** = |IVF top-k  ∩  brute top-k| / k per query, averaged — the
  brute arm IS the oracle for the IVF arm;
- timing is per-query wall time over single-row queries (the /neighbors
  shape), arm order ABBA within every round after one full discarded warm
  arm of EACH kind (the warm brute arm also absorbs the one
  H2D-per-mutation-burst upload; the warm IVF arm builds the probed
  lists' cached matrices), p50/p99 pooled per arm per rung. Results come
  back as host floats, so every timed query is already synced — the
  honest-sync rule is structural here.

The committed artifact is docs/evidence/retrieval_ab_r18.json; the
``retrieval_ab`` config in scripts/ratchet.py's DEFAULT list re-verifies
it (recall bar + bit-identity everywhere; the >=5x p50 speedup claim at
the top rung is CPU-calibrated and pass-skips off-CPU).

Usage: python scripts/retrieval_ab.py [--smoke] [--json OUT]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.serve.fleet.ivf import (  # noqa: E402
    IVFIndex,
    auto_nlist,
)
from simclr_pytorch_distributed_tpu.serve.fleet.retrieval import (  # noqa: E402
    NeighborIndex,
)

SCHEMA = "retrieval_ab/v1"
ARM_ORDER = ("brute", "ivf", "ivf", "brute")  # ABBA within every round
RECALL_BAR = 0.95
SPEEDUP_BAR = 5.0
INSERT_CHUNK = 8192  # /embed-burst-sized add() calls, same order both arms


def brute_oracle(corpus_unit, q_unit, k):
    """Frozen numpy restatement of the PR-17 brute scoring contract, for
    the bit-identity check: unit-dot scores over the corpus in slot order
    (insertion order — no eviction at capacity == rows), argpartition +
    stable argsort top-k. Deliberately NOT a call into the index."""
    scores = (q_unit @ corpus_unit.T).astype(np.float32, copy=False)
    out = []
    for row_scores in scores:
        k_eff = min(int(k), row_scores.shape[0])
        top = np.argpartition(-row_scores, k_eff - 1)[:k_eff]
        top = top[np.argsort(-row_scores[top], kind="stable")]
        out.append([(int(i), np.float32(row_scores[i])) for i in top])
    return out


def unit_rows(rows):
    rows = np.asarray(rows, np.float32)
    norms = np.linalg.norm(rows, axis=-1, keepdims=True)
    return rows / np.maximum(norms, 1e-12)


def percentile(values, p):
    return float(np.percentile(np.asarray(values, np.float64), p))


def build_output(device, params, rungs, oracle):
    """Assemble the committed artifact from per-rung records (pure, so
    tests pin the schema without running the measurement).

    ``rungs``: one dict per corpus size with the paired latency runs,
    pooled quantiles, recall, and index stats. ``oracle``: the brute
    bit-identity record."""
    per_rung = [
        {
            "rows": r["rows"],
            "recall_at_k": r["recall_at_k"],
            "speedup_p50": r["speedup_p50"],
            "brute_p50_ms": r["lat_ms"]["brute"]["p50"],
            "ivf_p50_ms": r["lat_ms"]["ivf"]["p50"],
            "brute_p99_ms": r["lat_ms"]["brute"]["p99"],
            "ivf_p99_ms": r["lat_ms"]["ivf"]["p99"],
        }
        for r in rungs
    ]
    top = max(rungs, key=lambda r: r["rows"])
    return {
        "schema": SCHEMA,
        "metric": "retrieval_query_ms",
        "params": params,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "rungs": rungs,
        "oracle": oracle,
        "summary": {
            "recall_bar": RECALL_BAR,
            "speedup_bar": SPEEDUP_BAR,
            "min_recall_at_k": min(r["recall_at_k"] for r in rungs),
            "max_rung_rows": top["rows"],
            "speedup_p50_max_rung": top["speedup_p50"],
            "per_rung": per_rung,
        },
        "device": device,
        "note": (
            "paired brute-vs-IVF /neighbors A/B over cluster-structured "
            "corpora: same rows, same content keys, same chunked insert "
            "order into both indexes; per-query single-row latency, ABBA "
            "arm order after one discarded warm arm of each kind; "
            "recall@k counts IVF hits against the brute top-k (the brute "
            "arm is the oracle); the brute arm itself is bit-checked "
            "(ids exact, float32 scores bitwise) against a frozen numpy "
            "restatement of the PR-17 scoring contract before any timing; "
            "query results are host floats, so every timed call is synced "
            "by construction"
        ),
    }


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default=None,
                    help="comma-separated corpus-size rungs; default "
                         "4096,65536,262144 (1024,4096 under --smoke)")
    ap.add_argument("--dim", type=positive_int, default=None,
                    help="embedding dim; default 64 (16 under --smoke)")
    ap.add_argument("--k", type=positive_int, default=10,
                    help="neighbors per query (recall is recall@k)")
    ap.add_argument("--queries", type=positive_int, default=None,
                    help="queries per timed arm run; default 32 (8 under "
                         "--smoke)")
    ap.add_argument("--rounds", type=positive_int, default=None,
                    help="ABBA rounds (2 measurements per arm per round); "
                         "default 2 (1 under --smoke)")
    ap.add_argument("--nlist", type=int, default=0,
                    help="IVF lists; 0 = sqrt(rows) per rung, clamped")
    ap.add_argument("--nprobe", type=positive_int, default=8,
                    help="IVF lists scanned per query")
    ap.add_argument("--noise", type=float, default=0.25,
                    help="cluster noise sigma (rows = center + sigma*N(0,1))")
    ap.add_argument("--seed", type=positive_int, default=7)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config for tests")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke fills only flags the caller left unset (flush_ab pattern)
    smoke_defaults = dict(rows="1024,4096", dim=16, queries=8, rounds=1)
    full_defaults = dict(rows="4096,65536,262144", dim=64, queries=32,
                         rounds=2)
    for key, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, key) is None:
            setattr(args, key, v)
    rung_rows = [positive_int(s) for s in args.rows.split(",")]

    import jax  # late: everything here is host numpy except brute's scorer

    device = jax.devices()[0].device_kind
    rungs = []
    oracle = {
        "ids_identical": True,
        "scores_bit_identical": True,
        "queries_checked": 0,
        "rungs_checked": [],
    }

    for rows_n in rung_rows:
        rng = np.random.default_rng((args.seed, rows_n))
        # cluster-structured corpus: served-embedding-like geometry
        n_clusters = max(16, rows_n // 512)
        centers = rng.standard_normal((n_clusters, args.dim)).astype(np.float32)
        which = rng.integers(0, n_clusters, rows_n)
        corpus = (
            centers[which]
            + args.noise * rng.standard_normal((rows_n, args.dim))
        ).astype(np.float32)
        keys = [f"r{i:07d}" for i in range(rows_n)]
        q = (
            centers[rng.integers(0, n_clusters, args.queries)]
            + args.noise
            * rng.standard_normal((args.queries, args.dim))
        ).astype(np.float32)

        nlist = args.nlist or auto_nlist(rows_n)
        brute = NeighborIndex(args.dim, capacity=rows_n)
        ivf = IVFIndex(args.dim, capacity=rows_n, nlist=nlist,
                       nprobe=args.nprobe, seed=args.seed)
        insert_ms = {}
        for arm, index in (("brute", brute), ("ivf", ivf)):
            t0 = time.perf_counter()
            for lo in range(0, rows_n, INSERT_CHUNK):
                index.add(keys[lo:lo + INSERT_CHUNK],
                          corpus[lo:lo + INSERT_CHUNK])
            insert_ms[arm] = round((time.perf_counter() - t0) * 1e3, 2)

        # ---- brute bit-identity vs the frozen oracle (gates the artifact,
        # before any timing) --------------------------------------------
        corpus_unit = unit_rows(corpus)
        q_unit = unit_rows(q)
        expected = brute_oracle(corpus_unit, q_unit, args.k)
        got = brute.query(q, args.k)
        for exp_row, got_row in zip(expected, got):
            exp_ids = [keys[i] for i, _ in exp_row]
            if exp_ids != [key for key, _ in got_row]:
                oracle["ids_identical"] = False
            if any(
                np.float32(score).tobytes() != exp_score.tobytes()
                for (_, exp_score), (_, score) in zip(exp_row, got_row)
            ):
                oracle["scores_bit_identical"] = False
        oracle["queries_checked"] += len(expected)
        oracle["rungs_checked"].append(rows_n)
        if not (oracle["ids_identical"] and oracle["scores_bit_identical"]):
            print(json.dumps({"oracle": oracle}), flush=True)
            raise SystemExit(
                f"brute rung diverged from the PR-17 oracle at {rows_n} rows"
            )

        # ---- recall@k: IVF against the brute answer ---------------------
        brute_top = [set(key for key, _ in row) for row in got]
        ivf_top = ivf.query(q, args.k)
        recall = float(np.mean([
            len(b & set(key for key, _ in v)) / max(1, len(b))
            for b, v in zip(brute_top, ivf_top)
        ]))

        # ---- timing: per-query latency, ABBA after discarded warms ------
        def run_arm(index):
            lats = []
            for row in q:
                t0 = time.perf_counter()
                res = index.query(row[None, :], args.k)
                lats.append((time.perf_counter() - t0) * 1e3)
            assert res[0] and np.isfinite(res[0][0][1])
            return lats

        arms = {"brute": brute, "ivf": ivf}
        warm = {arm: round(percentile(run_arm(index), 50), 4)
                for arm, index in arms.items()}
        print(json.dumps({"rows": rows_n,
                          "warmup_discarded_p50_ms": warm}), flush=True)
        pooled = {"brute": [], "ivf": []}
        runs = []
        for rnd in range(args.rounds):
            record = {"brute": [], "ivf": []}
            for arm in ARM_ORDER:
                lats = run_arm(arms[arm])
                pooled[arm].extend(lats)
                record[arm].append(round(percentile(lats, 50), 4))
            runs.append(record)
            print(json.dumps({"rows": rows_n, "round": rnd,
                              "p50_ms": record}), flush=True)

        lat_ms = {
            arm: {
                "p50": round(percentile(vals, 50), 4),
                "p99": round(percentile(vals, 99), 4),
                "n": len(vals),
            }
            for arm, vals in pooled.items()
        }
        ivf_stats = ivf.stats()
        rung = {
            "rows": rows_n,
            "clusters": n_clusters,
            "nlist": nlist,
            "nprobe": args.nprobe,
            "insert_ms": insert_ms,
            "runs": runs,
            "lat_ms": lat_ms,
            "recall_at_k": round(recall, 4),
            "speedup_p50": (
                round(lat_ms["brute"]["p50"] / lat_ms["ivf"]["p50"], 3)
                if lat_ms["ivf"]["p50"] > 0 else None
            ),
            "ivf_stats": {
                key: ivf_stats[key]
                for key in ("trained_lists", "retrains", "probes",
                            "evictions", "entries")
            },
        }
        rungs.append(rung)
        print(json.dumps({"rung": {
            "rows": rows_n, "recall_at_k": rung["recall_at_k"],
            "speedup_p50": rung["speedup_p50"], "lat_ms": lat_ms,
        }}), flush=True)

    params = {
        "dim": args.dim, "k": args.k, "queries": args.queries,
        "rounds": args.rounds, "nlist": args.nlist, "nprobe": args.nprobe,
        "noise": args.noise, "seed": args.seed, "smoke": bool(args.smoke),
        "insert_chunk": INSERT_CHUNK,
    }
    out = build_output(device, params, rungs, oracle)
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
