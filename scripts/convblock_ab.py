#!/usr/bin/env python
"""Do the fused Pallas conv-block kernels delete the inter-op HBM
round-trips that fund XLA's stage-1 conv/BN/residual fusions — for every
admitted block kind and compute dtype?

Two claims per block kind, one committed artifact
(docs/evidence/convblock_ab_r19.json, schema convblock_ab/v2):

**Parity (binds on every device).** Each fused kernel
(ops/pallas_conv.fused_basic_block / fused_projection_block /
fused_bottleneck_block, interpret mode) must match the bitwise-pinned
Flax block — forward value, ALL input/parameter gradients, and every BN
batch-statistic pair. fp32 kinds bind at the exact-accumulation
tolerances (value/stats <= 3e-5 abs; grads 1e-4 rtol + 1e-3 atol). bf16
kinds compare the bf16 kernel against the SAME fp32 Flax reference at
the round-19 derived tolerances (docs/PERF.md round 19: bf16 unit
roundoff 2^-8 ~= 3.9e-3; observed worst value scaled-error 5.9e-3 and
worst grad cosine 0.9905 across kinds/geometries — ReLU-mask flips near
zero pre-activations make per-entry grad maxabs the wrong metric, so
grads bind on cosine): value scaled-maxabs <= 2e-2 AND cosine >= 0.9999;
grads cosine >= 0.95 AND scaled-maxabs <= 0.5; BN stats scaled-maxabs
<= 2e-2. ``parity_ok`` gates each kind's timing section: a timing number
for a kernel that computes the wrong thing is worthless.

**Timing (CPU-calibrated proxy).** On CPU the real HBM is not the
bottleneck and a TPU Pallas kernel cannot compile, so — exactly like
``resident_ab``/``window_ab`` model the serialized tunnel link — this
proxy models the BANDWIDTH-BOUND regime the xplane evidence measured
(docs/PERF.md round 4: conv fusions at 69% of peak BW): both arms run
the SAME compiled block forward+backward step (arm math identical by
construction) and pay a fence + injected ``--hbm_delay_ms`` once per
modeled HBM traversal of the block's activation footprint, scaled by the
kind's ``bytes_scale`` (0.5 for bf16 — half the bytes per traversal is
the reason the bf16 kernels exist). The traversal counts are not free
parameters: the pallas counts are BlockSpec properties of
ops/pallas_conv.py (FWD/BWD_HBM_TRAVERSALS_{BLOCK,PROJ,BOTTLENECK} —
each stats phase re-reads its resident input tiles, outputs are written
once via the phase-gated index maps), and the xla counts follow the
round-4 fusion decomposition per kind (derivations in the
ops/pallas_conv.py constants and docs/PERF.md round 19). Arm order is
ABBA per round after one full discarded warm arm of each kind, and
every timed arm ends with a host readback of a COMPUTED scalar.

Expectation per kind: ``xla_ms - pallas_ms ~= delay * bytes_scale *
(T_xla - T_pallas)`` per step. The chip expectation derived from the
committed artifact lives in docs/PERF.md round 19, next to the honest
note that the end-to-end chip number is pending a chip-attached round.

Usage: python scripts/convblock_ab.py [--smoke] [--hbm_delay_ms N]
           [--rounds N] [--kinds basic proj ...] [--json OUT]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.models.resnet import (  # noqa: E402
    BasicBlock,
    Bottleneck,
)
from simclr_pytorch_distributed_tpu.ops import pallas_conv  # noqa: E402

SCHEMA = "convblock_ab/v2"
ARM_ORDER = ("xla", "pallas", "pallas", "xla")  # ABBA within every round

# fp32 parity tolerances (the tests' pins, restated for the artifact):
# fp32 accumulation-order noise between the shifted-matmul kernels and
# XLA's conv emitter
PARITY_VAL_TOL = 3e-5
PARITY_GRAD_RTOL = 1e-4
PARITY_GRAD_ATOL = 1e-3

# bf16 derived tolerances (docs/PERF.md round 19 derivation; the PR-3
# bf16-serving precedent of binding on agreement metrics, not bitwise)
BF16_VAL_SCALED_TOL = 2e-2
BF16_VAL_COS_FLOOR = 0.9999
BF16_GRAD_COS_FLOOR = 0.95
BF16_GRAD_SCALED_TOL = 0.5
BF16_STATS_SCALED_TOL = 2e-2

# per-kind modeled HBM traversals of the block's activation footprint per
# train step, each path — BlockSpec properties / round-4 decomposition
# (see the ops/pallas_conv.py constants' derivation comments)
TRAVERSALS = {
    "basic": {
        "xla": (pallas_conv.FWD_HBM_TRAVERSALS_XLA
                + pallas_conv.BWD_HBM_TRAVERSALS_XLA),
        "pallas": (pallas_conv.FWD_HBM_TRAVERSALS_BLOCK
                   + pallas_conv.BWD_HBM_TRAVERSALS_BLOCK),
    },
    "proj": {
        "xla": (pallas_conv.FWD_HBM_TRAVERSALS_PROJ_XLA
                + pallas_conv.BWD_HBM_TRAVERSALS_PROJ_XLA),
        "pallas": (pallas_conv.FWD_HBM_TRAVERSALS_PROJ
                   + pallas_conv.BWD_HBM_TRAVERSALS_PROJ),
    },
    "bottleneck": {
        "xla": (pallas_conv.FWD_HBM_TRAVERSALS_BOTTLENECK_XLA
                + pallas_conv.BWD_HBM_TRAVERSALS_BOTTLENECK_XLA),
        "pallas": (pallas_conv.FWD_HBM_TRAVERSALS_BOTTLENECK
                   + pallas_conv.BWD_HBM_TRAVERSALS_BOTTLENECK),
    },
}

BLOCK_KINDS = ("basic", "basic_bf16", "proj", "proj_bf16",
               "bottleneck", "bottleneck_bf16")


def _base_kind(kind):
    return kind[:-5] if kind.endswith("_bf16") else kind


def _dtype_tag(kind):
    return "bf16" if kind.endswith("_bf16") else "fp32"


def _bytes_scale(kind):
    # bf16 halves the bytes of every modeled activation traversal
    return 0.5 if kind.endswith("_bf16") else 1.0


def kind_geometry(kind, batch, size, channels):
    """Per-kind geometry derived from the three CLI knobs: the identity
    BasicBlock at (batch, size, channels), the projection block widening
    channels -> 2*channels at stride 2, the Bottleneck at planes=channels
    with a 2*channels input and a stride-2 projection shortcut (the new
    round-19 edges exercised where they differ most from round 15)."""
    base = _base_kind(kind)
    if base == "basic":
        return {"batch": batch, "h": size, "w": size,
                "in_channels": channels, "channels": channels, "stride": 1}
    if base == "proj":
        return {"batch": batch, "h": size, "w": size,
                "in_channels": channels, "channels": 2 * channels,
                "stride": 2}
    return {"batch": batch, "h": size, "w": size,
            "in_channels": 2 * channels, "planes": channels, "stride": 2}


def kind_supported(kind, geo):
    dtype = jnp.bfloat16 if _dtype_tag(kind) == "bf16" else jnp.float32
    base = _base_kind(kind)
    if base == "bottleneck":
        return pallas_conv.supports_bottleneck(
            geo["batch"], geo["h"], geo["w"], geo["planes"],
            stride=geo["stride"], in_channels=geo["in_channels"], dtype=dtype,
        )
    return pallas_conv.supports_block(
        geo["batch"], geo["h"], geo["w"], geo["channels"],
        stride=geo["stride"], in_channels=geo["in_channels"], dtype=dtype,
    )


def build_output(device, hbm_delay_ms, steps_per_arm, blocks):
    """Assemble the committed-artifact JSON from per-kind parity + round
    records (pure so tests pin the schema without running the
    measurement).

    ``blocks``: ``{kind: {"geometry", "dtype", "bytes_scale",
    "traversals", "parity", "runs"}}`` where runs is one dict per ABBA
    round, ``{"xla": [ms_per_step, ...], "pallas": [...]}`` (empty when
    that kind's parity is broken — timing for a wrong kernel is
    meaningless, but the artifact still carries the structured diffs)."""
    out_blocks = {}
    all_parity_ok = True
    for kind, b in blocks.items():
        runs = b.get("runs", [])
        all_xla = [v for r in runs for v in r["xla"]]
        all_pallas = [v for r in runs for v in r["pallas"]]
        xla_ms = statistics.median(all_xla) if all_xla else None
        pallas_ms = statistics.median(all_pallas) if all_pallas else None
        trav = b["traversals"]
        all_parity_ok = all_parity_ok and b["parity"]["parity_ok"]
        out_blocks[kind] = {
            "geometry": b["geometry"],
            "dtype": b["dtype"],
            "bytes_scale": b["bytes_scale"],
            "traversals": trav,
            "parity": b["parity"],
            "runs": runs,
            "summary": {
                "xla_ms_per_step": (
                    round(xla_ms, 2) if xla_ms is not None else None
                ),
                "pallas_ms_per_step": (
                    round(pallas_ms, 2) if pallas_ms is not None else None
                ),
                "traversal_removed_ms_per_step": (
                    round(xla_ms - pallas_ms, 2)
                    if xla_ms is not None and pallas_ms is not None else None
                ),
                "expected_removed_ms_per_step": round(
                    hbm_delay_ms * b["bytes_scale"]
                    * (trav["xla"] - trav["pallas"]), 2
                ),
                "speedup": (
                    round(xla_ms / pallas_ms, 3)
                    if xla_ms is not None and pallas_ms else None
                ),
            },
        }
    return {
        "schema": SCHEMA,
        "metric": "convblock_ab_ms_per_step",
        "hbm_delay_ms": hbm_delay_ms,
        "steps_per_arm": steps_per_arm,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "blocks": out_blocks,
        "parity_ok": bool(all_parity_ok),
        "device": device,
        "note": (
            "paired CPU-proxy A/B per block kind: both arms run the SAME "
            "compiled block fwd+bwd step (arm math identical by "
            "construction; the kernel-vs-flax contract is each kind's "
            "parity section) and pay fence + injected delay once per "
            "modeled HBM traversal scaled by bytes_scale (0.5 for bf16) "
            "— per-materialization for the XLA fusion decomposition, "
            "per-phase-read/write for the fused kernels; each timed arm "
            "ends with a computed-scalar readback; per-kind parity_ok "
            "gates that kind's timing"
        ),
    }


def _compare(pairs, stats_pairs, dtype_tag):
    """Per-tensor comparison -> the artifact's parity dict. ``pairs``:
    [(name, pallas_val, flax_ref)] with 'out' first; ``stats_pairs``:
    [(name, pallas_stat, flax_ref_stat)]."""
    def cosine(a, b):
        a = np.asarray(a, np.float64).ravel()
        b = np.asarray(b, np.float64).ravel()
        return float(np.dot(a, b)
                     / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-30))

    diffs, metrics = {}, {}
    value_ok = grads_ok = stats_ok = True
    for name, a, b in pairs:
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        maxabs = float(np.max(np.abs(a - b)))
        diffs[name] = maxabs
        if dtype_tag == "fp32":
            if name == "out":
                value_ok = value_ok and maxabs <= PARITY_VAL_TOL
            else:
                bound = (PARITY_GRAD_ATOL
                         + PARITY_GRAD_RTOL * float(np.max(np.abs(b))))
                grads_ok = grads_ok and maxabs <= bound
        else:
            scaled = maxabs / (float(np.max(np.abs(b))) + 1e-30)
            co = cosine(a, b)
            metrics[name] = {"cos": round(co, 6),
                             "scaled_maxabs": round(scaled, 6)}
            if name == "out":
                value_ok = value_ok and (
                    scaled <= BF16_VAL_SCALED_TOL and co >= BF16_VAL_COS_FLOOR
                )
            else:
                grads_ok = grads_ok and (
                    co >= BF16_GRAD_COS_FLOOR
                    and scaled <= BF16_GRAD_SCALED_TOL
                )
    for name, a, b in stats_pairs:
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        maxabs = float(np.max(np.abs(a - b)))
        diffs[name] = maxabs
        if dtype_tag == "fp32":
            stats_ok = stats_ok and maxabs <= PARITY_VAL_TOL
        else:
            scaled = maxabs / (float(np.max(np.abs(b))) + 1e-30)
            metrics[name] = {"scaled_maxabs": round(scaled, 6)}
            stats_ok = stats_ok and scaled <= BF16_STATS_SCALED_TOL
    parity = {
        "parity_ok": bool(value_ok and grads_ok and stats_ok),
        "value_ok": bool(value_ok),
        "grads_ok": bool(grads_ok),
        "stats_ok": bool(stats_ok),
        "max_abs_diffs": {k: round(v, 9) for k, v in diffs.items()},
        "tolerances": (
            {"value_atol": PARITY_VAL_TOL, "grad_rtol": PARITY_GRAD_RTOL,
             "grad_atol": PARITY_GRAD_ATOL, "stats_atol": PARITY_VAL_TOL}
            if dtype_tag == "fp32" else
            {"value_scaled_maxabs": BF16_VAL_SCALED_TOL,
             "value_cos_floor": BF16_VAL_COS_FLOOR,
             "grad_cos_floor": BF16_GRAD_COS_FLOOR,
             "grad_scaled_maxabs": BF16_GRAD_SCALED_TOL,
             "stats_scaled_maxabs": BF16_STATS_SCALED_TOL}
        ),
    }
    if metrics:
        parity["bf16_metrics"] = metrics
    return parity


def measure_parity(kind, geo, seed=0):
    """Interpret-mode fused kernel vs the (always-fp32) Flax block for one
    kind: value, every gradient, every BN batch-stat pair."""
    from simclr_pytorch_distributed_tpu.models.norm import running_stats_update

    dtype_tag = _dtype_tag(kind)
    in_dtype = jnp.bfloat16 if dtype_tag == "bf16" else jnp.float32
    base = _base_kind(kind)
    rng = np.random.default_rng(seed)

    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    def loss_of(out):
        return jnp.sum(out * jnp.cos(out))

    n, h, w, stride = geo["batch"], geo["h"], geo["w"], geo["stride"]
    cin = geo["in_channels"]
    x = arr(n, h, w, cin)
    ho, wo = h // stride, w // stride

    if base in ("basic", "proj"):
        c = geo["channels"]
        k1 = arr(3, 3, cin, c, scale=0.2)
        g1, b1 = arr(c, shift=1.0), arr(c, scale=0.1)
        k2 = arr(3, 3, c, c, scale=0.2)
        g2, b2 = arr(c, shift=1.0), arr(c, scale=0.1)
        mod = BasicBlock(planes=c, stride=stride)
        params = {"Conv_0": {"kernel": k1}, "bn1": {"scale": g1, "bias": b1},
                  "Conv_1": {"kernel": k2}, "bn2": {"scale": g2, "bias": b2}}
        stats = {"bn1": {"mean": jnp.zeros(c), "var": jnp.ones(c)},
                 "bn2": {"mean": jnp.zeros(c), "var": jnp.ones(c)}}
        names = ["dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2"]
        diff = [x, k1, g1, b1, k2, g2, b2]
        if base == "proj":
            ks = arr(1, 1, cin, c, scale=0.3)
            gs, bs = arr(c, shift=1.0), arr(c, scale=0.1)
            params["shortcut_conv"] = {"kernel": ks}
            params["shortcut_bn"] = {"scale": gs, "bias": bs}
            stats["shortcut_bn"] = {"mean": jnp.zeros(c), "var": jnp.ones(c)}
            names += ["dks", "dgs", "dbs"]
            diff += [ks, gs, bs]

        def rebuild(a):
            p = {"Conv_0": {"kernel": a[1]},
                 "bn1": {"scale": a[2], "bias": a[3]},
                 "Conv_1": {"kernel": a[4]},
                 "bn2": {"scale": a[5], "bias": a[6]}}
            if base == "proj":
                p["shortcut_conv"] = {"kernel": a[7]}
                p["shortcut_bn"] = {"scale": a[8], "bias": a[9]}
            return p

        def call_pal(*a):
            if base == "basic":
                return pallas_conv.fused_basic_block(
                    a[0].astype(in_dtype), *a[1:], interpret=True)
            return pallas_conv.fused_projection_block(
                a[0].astype(in_dtype), *a[1:], stride=stride, interpret=True)

        count = n * ho * wo if base == "proj" else n * h * w
        bn_moments = [("bn1", 1, 2, c, count), ("bn2", 3, 4, c, count)]
        if base == "proj":
            bn_moments.append(("shortcut_bn", 5, 6, c, count))
    else:  # bottleneck
        pln = geo["planes"]
        c4 = 4 * pln
        k1 = arr(1, 1, cin, pln, scale=0.3)
        g1, b1 = arr(pln, shift=1.0), arr(pln, scale=0.1)
        k2 = arr(3, 3, pln, pln, scale=0.2)
        g2, b2 = arr(pln, shift=1.0), arr(pln, scale=0.1)
        k3 = arr(1, 1, pln, c4, scale=0.3)
        g3, b3 = arr(c4, shift=1.0), arr(c4, scale=0.1)
        proj = stride != 1 or cin != c4
        mod = Bottleneck(planes=pln, stride=stride)
        params = {"Conv_0": {"kernel": k1}, "bn1": {"scale": g1, "bias": b1},
                  "Conv_1": {"kernel": k2}, "bn2": {"scale": g2, "bias": b2},
                  "Conv_2": {"kernel": k3}, "bn3": {"scale": g3, "bias": b3}}
        stats = {"bn1": {"mean": jnp.zeros(pln), "var": jnp.ones(pln)},
                 "bn2": {"mean": jnp.zeros(pln), "var": jnp.ones(pln)},
                 "bn3": {"mean": jnp.zeros(c4), "var": jnp.ones(c4)}}
        names = ["dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2",
                 "dk3", "dg3", "db3"]
        diff = [x, k1, g1, b1, k2, g2, b2, k3, g3, b3]
        if proj:
            ks = arr(1, 1, cin, c4, scale=0.3)
            gs, bs = arr(c4, shift=1.0), arr(c4, scale=0.1)
            params["shortcut_conv"] = {"kernel": ks}
            params["shortcut_bn"] = {"scale": gs, "bias": bs}
            stats["shortcut_bn"] = {"mean": jnp.zeros(c4), "var": jnp.ones(c4)}
            names += ["dks", "dgs", "dbs"]
            diff += [ks, gs, bs]

        def rebuild(a):
            p = {"Conv_0": {"kernel": a[1]},
                 "bn1": {"scale": a[2], "bias": a[3]},
                 "Conv_1": {"kernel": a[4]},
                 "bn2": {"scale": a[5], "bias": a[6]},
                 "Conv_2": {"kernel": a[7]},
                 "bn3": {"scale": a[8], "bias": a[9]}}
            if proj:
                p["shortcut_conv"] = {"kernel": a[10]}
                p["shortcut_bn"] = {"scale": a[11], "bias": a[12]}
            return p

        def call_pal(*a):
            sc = (a[10], a[11], a[12]) if proj else None
            return pallas_conv.fused_bottleneck_block(
                a[0].astype(in_dtype), a[1], a[2], a[3], a[4], a[5], a[6],
                a[7], a[8], a[9], sc, stride=stride, interpret=True)

        count1, count2 = n * h * w, n * ho * wo
        bn_moments = [("bn1", 1, 2, pln, count1), ("bn2", 3, 4, pln, count2),
                      ("bn3", 5, 6, c4, count2)]
        if proj:
            bn_moments.append(("shortcut_bn", 7, 8, c4, count2))

    def flax_out(*a):
        out, mut = mod.apply(
            {"params": rebuild(a), "batch_stats": stats}, a[0], True,
            mutable=["batch_stats"],
        )
        return out, mut["batch_stats"]

    argnums = tuple(range(len(diff)))
    r = call_pal(*diff)
    out_ref, stats_ref = flax_out(*diff)
    gp = jax.grad(
        lambda *a: loss_of(call_pal(*a)[0].astype(jnp.float32)),
        argnums=argnums,
    )(*diff)
    gr = jax.grad(lambda *a: loss_of(flax_out(*a)[0]), argnums=argnums)(*diff)

    pairs = [("out", r[0].astype(jnp.float32), out_ref)]
    pairs += list(zip(names, gp, gr))
    stats_pairs = []
    for bn_name, mi, vi, cc, cnt in bn_moments:
        ra_m, ra_v = running_stats_update(
            jnp.zeros(cc), jnp.ones(cc), r[mi], r[vi], cnt, 0.1
        )
        stats_pairs.append(
            (f"{bn_name}_mean", ra_m, stats_ref[bn_name]["mean"]))
        stats_pairs.append(
            (f"{bn_name}_var", ra_v, stats_ref[bn_name]["var"]))
    return _compare(pairs, stats_pairs, dtype_tag)


def make_train_step(kind, geo, seed=1):
    """One compiled block fwd+bwd 'step' for the timing arms: loss over
    the Flax block output, grads to the two 3x3/central conv kernels,
    tiny SGD-ish update — BOTH arms run exactly this program (the proxy's
    treatment is the traversal count x bytes_scale)."""
    base = _base_kind(kind)
    rng = np.random.default_rng(seed)
    n, h, w, stride = geo["batch"], geo["h"], geo["w"], geo["stride"]
    cin = geo["in_channels"]
    x0 = jnp.asarray(rng.standard_normal((n, h, w, cin)).astype(np.float32))

    def arr(*shape, scale=1.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale)

    if base in ("basic", "proj"):
        c = geo["channels"]
        mod = BasicBlock(planes=c, stride=stride)
        ka = arr(3, 3, cin, c, scale=0.2)
        kb = arr(3, 3, c, c, scale=0.2)

        def make_params(kk1, kk2):
            p = {"Conv_0": {"kernel": kk1},
                 "bn1": {"scale": jnp.ones(c), "bias": jnp.zeros(c)},
                 "Conv_1": {"kernel": kk2},
                 "bn2": {"scale": jnp.ones(c), "bias": jnp.zeros(c)}}
            s = {"bn1": {"mean": jnp.zeros(c), "var": jnp.ones(c)},
                 "bn2": {"mean": jnp.zeros(c), "var": jnp.ones(c)}}
            if base == "proj":
                p["shortcut_conv"] = {"kernel": arr(1, 1, cin, c, scale=0.3)}
                p["shortcut_bn"] = {"scale": jnp.ones(c),
                                    "bias": jnp.zeros(c)}
                s["shortcut_bn"] = {"mean": jnp.zeros(c), "var": jnp.ones(c)}
            return p, s
    else:
        pln = geo["planes"]
        c4 = 4 * pln
        mod = Bottleneck(planes=pln, stride=stride)
        ka = arr(1, 1, cin, pln, scale=0.3)
        kb = arr(3, 3, pln, pln, scale=0.2)

        def make_params(kk1, kk2):
            p = {"Conv_0": {"kernel": kk1},
                 "bn1": {"scale": jnp.ones(pln), "bias": jnp.zeros(pln)},
                 "Conv_1": {"kernel": kk2},
                 "bn2": {"scale": jnp.ones(pln), "bias": jnp.zeros(pln)},
                 "Conv_2": {"kernel": arr(1, 1, pln, c4, scale=0.3)},
                 "bn3": {"scale": jnp.ones(c4), "bias": jnp.zeros(c4)},
                 "shortcut_conv": {"kernel": arr(1, 1, cin, c4, scale=0.3)},
                 "shortcut_bn": {"scale": jnp.ones(c4),
                                 "bias": jnp.zeros(c4)}}
            s = {"bn1": {"mean": jnp.zeros(pln), "var": jnp.ones(pln)},
                 "bn2": {"mean": jnp.zeros(pln), "var": jnp.ones(pln)},
                 "bn3": {"mean": jnp.zeros(c4), "var": jnp.ones(c4)},
                 "shortcut_bn": {"mean": jnp.zeros(c4), "var": jnp.ones(c4)}}
            return p, s

    @jax.jit
    def train_step(kk1, kk2):
        def loss(kk1, kk2):
            p, s = make_params(kk1, kk2)
            out, _ = mod.apply(
                {"params": p, "batch_stats": s}, x0, True,
                mutable=["batch_stats"],
            )
            return jnp.mean(jnp.square(out))

        l, (dk1, dk2) = jax.value_and_grad(loss, argnums=(0, 1))(kk1, kk2)
        return l, kk1 - 1e-3 * dk1, kk2 - 1e-3 * dk2

    return train_step, ka, kb


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    def nonneg_float(s):
        v = float(s)
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--hbm_delay_ms", type=nonneg_float, default=None,
                    help="injected per-traversal delay; default 5 ms, 10 ms "
                         "under --smoke (the injected stall must dominate "
                         "the tiny-block compute so the effect clears "
                         "1-core timer/contention noise — the window_ab "
                         "convention)")
    ap.add_argument("--steps", type=positive_int, default=None,
                    help="timed steps per arm; default 8, 2 under --smoke")
    ap.add_argument("--rounds", type=positive_int, default=2,
                    help="ABBA rounds (2 measurements per arm per round)")
    ap.add_argument("--batch", type=positive_int, default=None,
                    help="block batch rows; default 32, 16 under --smoke")
    ap.add_argument("--size", type=positive_int, default=None,
                    help="spatial side; default 16, 8 under --smoke")
    ap.add_argument("--channels", type=positive_int, default=None,
                    help="base block width (kind_geometry derives the "
                         "proj/bottleneck shapes); default 16, 8 under "
                         "--smoke")
    ap.add_argument("--kinds", nargs="+", choices=BLOCK_KINDS,
                    default=list(BLOCK_KINDS),
                    help="block-kind sections to run; default all six")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config for tests and the committed-"
                         "artifact run")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke fills only flags the caller left unset (flush_ab pattern)
    smoke_defaults = dict(batch=16, size=8, channels=8, steps=2,
                          hbm_delay_ms=10.0)
    full_defaults = dict(batch=32, size=16, channels=16, steps=8,
                         hbm_delay_ms=5.0)
    for k, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    delay_s = args.hbm_delay_ms / 1e3
    blocks = {}
    any_parity_broken = False
    for kind in args.kinds:
        geo = kind_geometry(kind, args.batch, args.size, args.channels)
        if not kind_supported(kind, geo):
            raise SystemExit(f"{kind}: geometry {geo} not admitted")
        base = _base_kind(kind)
        trav = TRAVERSALS[base]
        scale = _bytes_scale(kind)

        # ---- parity (gates this kind's timing, before any timing) -------
        parity = measure_parity(kind, geo)
        print(json.dumps({"kind": kind, "parity": parity}), flush=True)
        entry = {"geometry": geo, "dtype": _dtype_tag(kind),
                 "bytes_scale": scale, "traversals": trav,
                 "parity": parity, "runs": []}
        blocks[kind] = entry
        if not parity["parity_ok"]:
            any_parity_broken = True
            continue

        # ---- timing -----------------------------------------------------
        train_step, kk1, kk2 = make_train_step(kind, geo)

        def run_arm(mode, kk1, kk2):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                # serialized-link model (resident_ab/window_ab convention):
                # a bandwidth-bound chip pays its HBM time serially with
                # compute — fence the in-flight step, then pay one
                # bytes-scaled delay per modeled traversal of the
                # activation footprint
                jax.block_until_ready((kk1, kk2))
                for _ in range(trav[mode]):
                    time.sleep(delay_s * scale)
                l, kk1, kk2 = train_step(kk1, kk2)
            # honest sync: a computed scalar cannot exist until the steps
            # ran
            assert np.isfinite(float(l))
            dt = time.perf_counter() - t0
            return kk1, kk2, dt * 1e3 / args.steps

        # warmup: compile + ONE FULL DISCARDED ARM OF EACH KIND
        kk1, kk2, warm_x = run_arm("xla", kk1, kk2)
        kk1, kk2, warm_p = run_arm("pallas", kk1, kk2)
        print(json.dumps({"kind": kind, "warmup_discarded_ms_per_step":
                          {"xla": round(warm_x, 2),
                           "pallas": round(warm_p, 2)}}), flush=True)

        for rnd in range(args.rounds):
            record = {"xla": [], "pallas": []}
            for mode in ARM_ORDER:
                kk1, kk2, ms = run_arm(mode, kk1, kk2)
                record[mode].append(round(ms, 2))
                print(json.dumps({"kind": kind, "round": rnd, "arm": mode,
                                  "ms_per_step": round(ms, 2)}), flush=True)
            entry["runs"].append(record)

    out = build_output(
        jax.devices()[0].device_kind, args.hbm_delay_ms, args.steps, blocks,
    )
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    if any_parity_broken:
        raise SystemExit("parity BROKEN: timing would be meaningless")
    return out


if __name__ == "__main__":
    main()
