#!/usr/bin/env python
"""Does the fused Pallas conv-block kernel delete the inter-op HBM
round-trips that fund XLA's stage-1 conv/BN/residual fusions?

Two claims, two sections, one committed artifact
(docs/evidence/convblock_ab_r15.json):

**Parity (binds on every device).** The fused residual-block kernel
(ops/pallas_conv.fused_basic_block, interpret mode) must match the
bitwise-pinned Flax BasicBlock — forward value, all seven input/parameter
gradients, and both BN batch-statistic pairs — within pinned tolerances.
``parity_ok`` gates the artifact: a timing number for a kernel that
computes the wrong thing is worthless.

**Timing (CPU-calibrated proxy).** On CPU the real HBM is not the
bottleneck and a TPU Pallas kernel cannot compile, so — exactly like
``resident_ab``/``window_ab`` model the serialized tunnel link — this
proxy models the BANDWIDTH-BOUND regime the xplane evidence measured
(docs/PERF.md round 4: conv fusions at 69% of peak BW, the step at 0.85
of its mixed roofline): both arms run the SAME compiled block
forward+backward step (so arm math is identical by construction) and pay
a fence + injected ``--hbm_delay_ms`` once per modeled HBM traversal of
the block's activation footprint. The traversal counts are not free
parameters: the pallas counts are properties of the kernel's BlockSpecs
(ops/pallas_conv.FWD/BWD_HBM_TRAVERSALS_BLOCK — each stats phase re-reads
its input tiles, outputs are written once via the phase-gated index
maps), and the xla counts follow the round-4 fusion decomposition
(conv->BN-stat->normalize/ReLU->conv->BN-stat->residual chains,
fusion.81/74/75-class backward; FWD/BWD_HBM_TRAVERSALS_XLA, derivation in
the module docstring there). Arm order is ABBA per round after one full
discarded warm arm of each kind, and every timed arm ends with a host
readback of a COMPUTED scalar.

Expectation: ``xla_ms - pallas_ms ~= delay * (T_xla - T_pallas)`` per
step. The chip expectation derived from the committed artifact lives in
docs/PERF.md round 15, next to the honest note that the end-to-end chip
number is pending a chip-attached round.

Usage: python scripts/convblock_ab.py [--smoke] [--hbm_delay_ms N] [--json OUT]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.models.resnet import BasicBlock  # noqa: E402
from simclr_pytorch_distributed_tpu.ops import pallas_conv  # noqa: E402

SCHEMA = "convblock_ab/v1"
ARM_ORDER = ("xla", "pallas", "pallas", "xla")  # ABBA within every round

# parity tolerances (the tests' pins, restated for the artifact): fp32
# accumulation-order noise between the 9-shifted-matmul kernel and XLA's
# conv emitter
PARITY_VAL_TOL = 3e-5
PARITY_GRAD_RTOL = 1e-4
PARITY_GRAD_ATOL = 1e-3

# modeled per-step HBM traversals of one fused block apply (fwd+bwd), per
# path — see the module docstrings here and in ops/pallas_conv.py
TRAVERSALS_PALLAS = (
    pallas_conv.FWD_HBM_TRAVERSALS_BLOCK + pallas_conv.BWD_HBM_TRAVERSALS_BLOCK
)
TRAVERSALS_XLA = (
    pallas_conv.FWD_HBM_TRAVERSALS_XLA + pallas_conv.BWD_HBM_TRAVERSALS_XLA
)


def build_output(device, hbm_delay_ms, geometry, steps_per_arm,
                 rounds_records, parity):
    """Assemble the committed-artifact JSON from per-round arm timings
    (pure so tests pin the schema without running the measurement).

    ``rounds_records``: one dict per round, ``{"xla": [ms_per_step, ...],
    "pallas": [...]}`` — two measurements per arm per round (ABBA).
    """
    all_xla = [v for r in rounds_records for v in r["xla"]]
    all_pallas = [v for r in rounds_records for v in r["pallas"]]
    # a broken-parity run carries NO timed rounds (timing for a wrong
    # kernel is meaningless) but must still write the artifact so the
    # ratchet gate can carry the structured per-tensor diffs
    xla_ms = statistics.median(all_xla) if all_xla else None
    pallas_ms = statistics.median(all_pallas) if all_pallas else None
    return {
        "schema": SCHEMA,
        "metric": "convblock_ab_ms_per_step",
        "hbm_delay_ms": hbm_delay_ms,
        "geometry": geometry,
        "steps_per_arm": steps_per_arm,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "traversals": {
            "xla": TRAVERSALS_XLA,
            "pallas": TRAVERSALS_PALLAS,
            "note": (
                "modeled HBM traversals of the block's activation "
                "footprint per train step (fwd+bwd); pallas counts are "
                "BlockSpec properties of ops/pallas_conv.py, xla counts "
                "follow the round-4 xplane fusion decomposition "
                "(docs/evidence/xplane_bw_r4.json)"
            ),
        },
        "runs": rounds_records,
        "parity": parity,
        "summary": {
            "xla_ms_per_step": round(xla_ms, 2) if xla_ms is not None else None,
            "pallas_ms_per_step": (
                round(pallas_ms, 2) if pallas_ms is not None else None
            ),
            "traversal_removed_ms_per_step": (
                round(xla_ms - pallas_ms, 2)
                if xla_ms is not None and pallas_ms is not None else None
            ),
            "expected_removed_ms_per_step": round(
                hbm_delay_ms * (TRAVERSALS_XLA - TRAVERSALS_PALLAS), 2
            ),
            "speedup": (
                round(xla_ms / pallas_ms, 3)
                if xla_ms is not None and pallas_ms else None
            ),
        },
        "device": device,
        "note": (
            "paired CPU-proxy A/B: both arms run the SAME compiled block "
            "fwd+bwd step (arm math identical by construction; the kernel-"
            "vs-flax contract is the parity section) and pay fence + "
            "injected delay once per modeled HBM traversal — per-"
            "materialization for the XLA fusion decomposition, per-phase-"
            "read/write for the fused kernel; each timed arm ends with a "
            "computed-scalar readback; parity_ok gates the artifact"
        ),
    }


def measure_parity(n, h, w, c, seed=0):
    """Interpret-mode fused block vs the Flax BasicBlock: max abs diffs
    for value, each gradient, and the BN batch stats; parity_ok under the
    pinned tolerances."""
    rng = np.random.default_rng(seed)

    def arr(*shape, scale=1.0, shift=0.0):
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32) * scale + shift
        )

    x = arr(n, h, w, c)
    k1, k2 = arr(3, 3, c, c, scale=0.2), arr(3, 3, c, c, scale=0.2)
    g1, g2 = arr(c, shift=1.0), arr(c, shift=1.0)
    b1, b2 = arr(c, scale=0.1), arr(c, scale=0.1)

    mod = BasicBlock(planes=c)
    variables = {
        "params": {
            "Conv_0": {"kernel": k1}, "bn1": {"scale": g1, "bias": b1},
            "Conv_1": {"kernel": k2}, "bn2": {"scale": g2, "bias": b2},
        },
        "batch_stats": {
            "bn1": {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))},
            "bn2": {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))},
        },
    }

    def flax_out(*a):
        xv, kk1, gg1, bb1, kk2, gg2, bb2 = a
        vs = {
            "params": {
                "Conv_0": {"kernel": kk1}, "bn1": {"scale": gg1, "bias": bb1},
                "Conv_1": {"kernel": kk2}, "bn2": {"scale": gg2, "bias": bb2},
            },
            "batch_stats": variables["batch_stats"],
        }
        out, mut = mod.apply(vs, xv, True, mutable=["batch_stats"])
        return out, mut["batch_stats"]

    args = (x, k1, g1, b1, k2, g2, b2)
    out_f, m1, v1, m2, v2 = pallas_conv.fused_basic_block(
        *args, interpret=True
    )
    out_r, stats_r = flax_out(*args)

    def scalar_loss(out):
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(
        lambda *a: scalar_loss(
            pallas_conv.fused_basic_block(*a, interpret=True)[0]
        ),
        argnums=tuple(range(7)),
    )(*args)
    gr = jax.grad(
        lambda *a: scalar_loss(flax_out(*a)[0]), argnums=tuple(range(7))
    )(*args)

    from simclr_pytorch_distributed_tpu.models.norm import running_stats_update

    count = n * h * w
    diffs = {"out": float(jnp.max(jnp.abs(out_f - out_r)))}
    names = ("dx", "dk1", "dg1", "db1", "dk2", "dg2", "db2")
    grads_ok = True
    for name, a, b in zip(names, gf, gr):
        d = float(jnp.max(jnp.abs(a - b)))
        diffs[name] = d
        bound = PARITY_GRAD_ATOL + PARITY_GRAD_RTOL * float(jnp.max(jnp.abs(b)))
        grads_ok = grads_ok and d <= bound
    stats_ok = True
    for bn_name, (m, v) in (("bn1", (m1, v1)), ("bn2", (m2, v2))):
        ra_m, ra_v = running_stats_update(
            jnp.zeros((c,)), jnp.ones((c,)), m, v, count, 0.1
        )
        dm = float(jnp.max(jnp.abs(ra_m - stats_r[bn_name]["mean"])))
        dv = float(jnp.max(jnp.abs(ra_v - stats_r[bn_name]["var"])))
        diffs[f"{bn_name}_mean"] = dm
        diffs[f"{bn_name}_var"] = dv
        stats_ok = stats_ok and max(dm, dv) <= PARITY_VAL_TOL
    value_ok = diffs["out"] <= PARITY_VAL_TOL
    return {
        "parity_ok": bool(value_ok and grads_ok and stats_ok),
        "value_ok": bool(value_ok),
        "grads_ok": bool(grads_ok),
        "stats_ok": bool(stats_ok),
        "max_abs_diffs": {k: round(v, 9) for k, v in diffs.items()},
        "tolerances": {
            "value_atol": PARITY_VAL_TOL,
            "grad_rtol": PARITY_GRAD_RTOL,
            "grad_atol": PARITY_GRAD_ATOL,
        },
    }


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    def nonneg_float(s):
        v = float(s)
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--hbm_delay_ms", type=nonneg_float, default=None,
                    help="injected per-traversal delay; default 5 ms, 20 ms "
                         "under --smoke (the injected stall must dominate "
                         "the tiny-block compute so the effect clears "
                         "1-core timer/contention noise — the window_ab "
                         "convention)")
    ap.add_argument("--steps", type=positive_int, default=None,
                    help="timed steps per arm; default 12, 4 under --smoke")
    ap.add_argument("--rounds", type=positive_int, default=2,
                    help="ABBA rounds (2 measurements per arm per round)")
    ap.add_argument("--batch", type=positive_int, default=None,
                    help="block batch rows; default 32, 16 under --smoke")
    ap.add_argument("--size", type=positive_int, default=None,
                    help="spatial side; default 16, 8 under --smoke")
    ap.add_argument("--channels", type=positive_int, default=None,
                    help="block width; default 16, 8 under --smoke")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config for tests and the committed-"
                         "artifact run")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke fills only flags the caller left unset (flush_ab pattern)
    smoke_defaults = dict(batch=16, size=8, channels=8, steps=4,
                          hbm_delay_ms=20.0)
    full_defaults = dict(batch=32, size=16, channels=16, steps=12,
                         hbm_delay_ms=5.0)
    for k, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    n, h, w, c = args.batch, args.size, args.size, args.channels
    if not pallas_conv.supports_block(n, h, w, c):
        raise SystemExit(f"geometry [{n},{h},{w},{c}] not admitted")
    delay_s = args.hbm_delay_ms / 1e3
    geometry = {"batch": n, "h": h, "w": w, "channels": c}

    # ---- parity (gates the artifact, before any timing) -----------------
    parity = measure_parity(n, h, w, c)
    print(json.dumps({"parity": parity}), flush=True)
    if not parity["parity_ok"]:
        out = build_output(
            jax.devices()[0].device_kind, args.hbm_delay_ms,
            geometry, args.steps, [], parity,
        )
        print(json.dumps(out))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(out, f, indent=1)
        raise SystemExit("parity BROKEN: timing would be meaningless")

    # ---- timing ---------------------------------------------------------
    rng = np.random.default_rng(1)
    x0 = jnp.asarray(rng.standard_normal((n, h, w, c)).astype(np.float32))
    k1 = jnp.asarray(
        rng.standard_normal((3, 3, c, c)).astype(np.float32) * 0.2
    )
    k2 = jnp.asarray(
        rng.standard_normal((3, 3, c, c)).astype(np.float32) * 0.2
    )
    g1 = jnp.ones((c,), jnp.float32)
    b1 = jnp.zeros((c,), jnp.float32)
    g2 = jnp.ones((c,), jnp.float32)
    b2 = jnp.zeros((c,), jnp.float32)

    mod = BasicBlock(planes=c)

    @jax.jit
    def train_step(xv, kk1, kk2):
        """One block fwd+bwd 'step': loss over the block output, grads to
        the conv kernels, tiny SGD-ish update — BOTH arms run exactly
        this program (the proxy's treatment is the traversal count)."""

        def loss(kk1, kk2):
            vs = {
                "params": {
                    "Conv_0": {"kernel": kk1},
                    "bn1": {"scale": g1, "bias": b1},
                    "Conv_1": {"kernel": kk2},
                    "bn2": {"scale": g2, "bias": b2},
                },
                "batch_stats": {
                    "bn1": {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))},
                    "bn2": {"mean": jnp.zeros((c,)), "var": jnp.ones((c,))},
                },
            }
            out, _ = mod.apply(vs, xv, True, mutable=["batch_stats"])
            return jnp.mean(jnp.square(out))

        l, (dk1, dk2) = jax.value_and_grad(loss, argnums=(0, 1))(kk1, kk2)
        return l, kk1 - 1e-3 * dk1, kk2 - 1e-3 * dk2

    traversal_count = {"xla": TRAVERSALS_XLA, "pallas": TRAVERSALS_PALLAS}

    def run_arm(mode, kk1, kk2):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            # serialized-link model (resident_ab/window_ab convention): a
            # bandwidth-bound chip pays its HBM time serially with compute
            # — fence the in-flight step, then pay one delay per modeled
            # traversal of the activation footprint
            jax.block_until_ready((kk1, kk2))
            for _ in range(traversal_count[mode]):
                time.sleep(delay_s)
            l, kk1, kk2 = train_step(x0, kk1, kk2)
        # honest sync: a computed scalar cannot exist until the steps ran
        assert np.isfinite(float(l))
        dt = time.perf_counter() - t0
        return kk1, kk2, dt * 1e3 / args.steps

    # warmup: compile + ONE FULL DISCARDED ARM OF EACH KIND
    kk1, kk2 = k1, k2
    kk1, kk2, warm_x = run_arm("xla", kk1, kk2)
    kk1, kk2, warm_p = run_arm("pallas", kk1, kk2)
    print(json.dumps({"warmup_discarded_ms_per_step":
                      {"xla": round(warm_x, 2),
                       "pallas": round(warm_p, 2)}}), flush=True)

    rounds_records = []
    for rnd in range(args.rounds):
        record = {"xla": [], "pallas": []}
        for mode in ARM_ORDER:
            kk1, kk2, ms = run_arm(mode, kk1, kk2)
            record[mode].append(round(ms, 2))
            print(json.dumps({"round": rnd, "arm": mode,
                              "ms_per_step": round(ms, 2)}), flush=True)
        rounds_records.append(record)

    out = build_output(
        jax.devices()[0].device_kind, args.hbm_delay_ms, geometry,
        args.steps, rounds_records, parity,
    )
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
