#!/usr/bin/env python
"""The supervisor scenario matrix — the REAL supervisor babysitting the REAL
driver through the four failure shapes, producing the committed evidence
artifact ``docs/evidence/supervisor_r11.json`` that ``scripts/ratchet.py``'s
``supervisor_gate`` verifies.

Scenarios (each in its own workdir; the victim is
``scripts/supervisor_victim.py`` — a 7-step/epoch synthetic pretrain with
one-shot injectable faults):

- ``sigkill``: SIGKILL lands mid-run (no grace, torn async save possible);
  the supervisor must observe the signal death, restart with ``--resume``
  (resolution picks the newest COMPLETE save), and the job must finish —
  decisions ``backoff_restart`` then ``done``;
- ``stall``: the victim's main thread wedges at a flush boundary (and
  absorbs SIGTERM via the preempt flag, like a dead collective); the
  supervisor must see liveness die — the scraped
  ``train_last_boundary_age_seconds`` climbing past the deadline, plus the
  in-child watchdog's stall dump in the run dir — kill through the grace
  escalation, and resume;
- ``collapse``: impossible health thresholds force a representation-health
  abort (typed exit 3) under ``--health_policy abort``; the supervisor must
  GIVE UP (collapse lives in the weights — docs/RESILIENCE.md precedence),
  exiting with the child's code;
- ``preempt_resize``: a ``resize_request`` file arrives mid-run; the
  supervisor gracefully preempts (SIGTERM -> emergency save -> exit 75)
  and relaunches ``--resume`` onto the new virtual-mesh device count —
  the elastic-resume proof (mesh-shape-agnostic restore,
  utils/checkpoint.py) driven end to end.

Two further scenarios land in a SEPARATE artifact
(``docs/evidence/chaos_matrix_r16.json``, verified by ratchet's
``chaos_matrix`` config) — the straggler-mitigation proof:

- ``straggler``: the supervisor babysits a REAL 2-process gloo fleet
  (``scripts/fleet_launcher.py`` wrapping ``tests/multiprocess_child.py``
  driver mode) whose process 1 is paced 150 ms at every boundary
  allgather; the REAL skew gauges cross the sidecar, the K-of-N detector
  declares persistence, and mitigation actuates end to end: graceful
  preempt -> fleet-wide exit 75 -> ``restart_rebalanced`` carrying
  ``FLEET_SHARE_HINT`` into the relaunched fleet's environment -> done.
  A policy-off control run of the same launcher proves the mitigated
  run's final parameter digests are bit-identical — mitigation changes
  WHERE work runs, never WHAT is computed;
- ``chaos``: the composed run — straggler skew AND a SIGKILL AND an
  injected representation-health collapse (under ``--health_policy
  warn``) in ONE supervised lifetime; the supervisor must drive
  rebalance, then absorb the kill, then land the fleet green —
  ``restart_rebalanced`` -> ``backoff_restart`` -> ``done``, exit 0,
  health alarms on the record throughout.

Each scenario prints one JSON line and lands in its artifact with its
decision sequence, exit code, and the supervisor events file it came from.

Usage:
    python scripts/supervisor_matrix.py --json docs/evidence/supervisor_r11.json
    python scripts/supervisor_matrix.py --scenarios straggler chaos \
        --chaos_json docs/evidence/chaos_matrix_r16.json
"""

import argparse
import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from simclr_pytorch_distributed_tpu.supervise import (  # noqa: E402
    SuperviseConfig,
    Supervisor,
)
from simclr_pytorch_distributed_tpu.supervise.launch import (  # noqa: E402
    find_resume_dir,
)

VICTIM = os.path.join(REPO, "scripts", "supervisor_victim.py")
LAUNCHER = os.path.join(REPO, "scripts", "fleet_launcher.py")
WAIT_S = 600.0  # per-wait ceiling (cold sharded compiles on a slow host)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(predicate, what: str, timeout_s: float = WAIT_S):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.25)
    raise RuntimeError(f"timeout waiting for {what}")


def _run_supervisor(cfg: SuperviseConfig):
    """Run the supervisor on a thread; returns (supervisor, join->rc)."""
    sup = Supervisor(cfg)
    box = {}

    def target():
        box["rc"] = sup.run()

    t = threading.Thread(target=target, name="supervisor", daemon=True)
    t.start()

    def join(timeout_s: float = WAIT_S) -> int:
        t.join(timeout_s)
        if t.is_alive():
            raise RuntimeError("supervisor did not finish")
        return box["rc"]

    return sup, join


def _events(sup: Supervisor):
    return [json.loads(line) for line in open(sup.recorder._path)]


def _record(name, sup, rc, expect_actions, detail=None):
    actions = [d.action for d in sup.decisions]
    events = _events(sup)
    rec = {
        "scenario": name,
        "rc": rc,
        "decisions": actions,
        "expected_decisions": list(expect_actions),
        "attempts": sum(1 for e in events if e["name"] == "launch"),
        "events_file": os.path.relpath(sup.recorder._path, REPO),
        "n_events": len(events),
        "ok": actions == list(expect_actions),
        **(detail or {}),
    }
    return rec, events


def _victim_cmd(workdir, **kw):
    cmd = [sys.executable, VICTIM, "--workdir", workdir]
    for k, v in kw.items():
        cmd += [f"--{k}", str(v)]
    return cmd


def _wait_for_checkpoint(workdir, name="ckpt_epoch_1"):
    def check():
        run_dir = find_resume_dir(workdir)
        if run_dir and os.path.exists(os.path.join(run_dir, name, "meta.json")):
            return run_dir
        return None

    return _wait_for(check, f"{name} in {workdir}")


def scenario_sigkill(base):
    # ckpt_epoch_1's async meta stamps at epoch 2's save drain, so the kill
    # lands around epoch 3 of 4 — strictly mid-run, with a complete save on
    # disk for the resume (the fault-harness kill9 geometry)
    wd = os.path.join(base, "sigkill")
    cfg = SuperviseConfig(
        command=_victim_cmd(wd, epochs=4, trial="k9", save_freq=1),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
    )
    sup, join = _run_supervisor(cfg)
    _wait_for_checkpoint(wd)
    pid = sup.child.pid
    os.kill(pid, signal.SIGKILL)
    rc = join()
    rec, _ = _record(
        "sigkill", sup, rc, ["backoff_restart", "done"],
        detail={"killed_pid": pid},
    )
    rec["ok"] = rec["ok"] and rc == 0
    return rec


def scenario_stall(base):
    wd = os.path.join(base, "stall")
    os.makedirs(wd, exist_ok=True)
    port = _free_port()
    cfg = SuperviseConfig(
        # 7 requested_global calls per complete epoch (6 mid-epoch
        # boundaries + the epoch edge): fault_step=16 wedges the main
        # thread at epoch 3 boundary 2 — AFTER ckpt_epoch_1's meta stamped
        # (epoch 2's save drain), so the post-kill resume has a complete
        # save to resolve. watchdog_secs must exceed the child's STARTUP
        # (jax import + first-step trace) — the watchdog arms at
        # construction, and a pre-first-boundary false dump would be read
        # as a stall verdict (the supervisor kills on the child's own dump
        # by design); 15s clears a warm-cache startup severalfold while the
        # real stall, which never beats again, still trips it
        command=_victim_cmd(
            wd, epochs=3, trial="stall", save_freq=1, fault="stall",
            fault_step=16, fault_marker=os.path.join(wd, "stall.marker"),
            metrics_port=port, watchdog_secs=15,
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        stall_secs=25.0, grace_secs=3.0, metrics_port=port,
    )
    sup, join = _run_supervisor(cfg)
    rc = join()
    rec, events = _record("stall", sup, rc, ["backoff_restart", "done"])
    stall_events = [e for e in events if e["name"] == "liveness_stall"]
    dump_events = [e for e in events if e["name"] == "stall_dump_observed"]
    rec["liveness_stalls"] = len(stall_events)
    rec["watchdog_dumps_observed"] = len(dump_events)
    # the decision must have come from a LIVENESS verdict, and the
    # in-child watchdog's artifact must have been surfaced too
    rec["ok"] = bool(rec["ok"] and rc == 0 and stall_events and dump_events)
    return rec


def scenario_collapse(base):
    wd = os.path.join(base, "collapse")
    cfg = SuperviseConfig(
        command=_victim_cmd(
            wd, epochs=1, trial="collapse", fault="collapse",
            health_freq=2, health_policy="abort",
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
    )
    sup, join = _run_supervisor(cfg)
    rc = join()
    rec, events = _record("collapse", sup, rc, ["give_up"])
    alarms = [
        e for e in events
        if e["name"] == "trainer_event"
        and e.get("args", {}).get("event") == "health_alarm"
    ]
    rec["health_alarms_observed"] = len(alarms)
    rec["ok"] = bool(rec["ok"] and rc == 3 and alarms)
    return rec


def scenario_preempt_resize(base, devices_before=8, devices_after=4):
    wd = os.path.join(base, "preempt_resize")
    # epochs=4: the resize request (written once ckpt_epoch_1's meta is
    # stamped, i.e. ~epoch 3) catches the child strictly mid-run; the
    # generous grace covers the SIGTERM -> flush-boundary -> emergency-save
    # exit-75 sequence on a slow host
    cfg = SuperviseConfig(
        command=_victim_cmd(wd, epochs=4, trial="resize", save_freq=1),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        grace_secs=120.0, devices=devices_before,
    )
    sup, join = _run_supervisor(cfg)
    _wait_for_checkpoint(wd)
    with open(os.path.join(sup.supervise_dir, "resize_request"), "w") as f:
        f.write(str(devices_after))
    rc = join()
    rec, events = _record(
        "preempt_resize", sup, rc, ["restart_resized", "done"],
        detail={"devices_before": devices_before,
                "devices_after": devices_after},
    )
    launches = [e["args"] for e in events if e["name"] == "launch"]
    rec["launch_devices"] = [la.get("devices") for la in launches]
    resized = [la for la in launches if la.get("devices") == devices_after]
    # the relaunch must land on the NEW topology AND resume the old run
    rec["resumed_resized"] = bool(resized and resized[0].get("resume"))
    rec["ok"] = bool(rec["ok"] and rc == 0 and rec["resumed_resized"])
    return rec


def _fleet_cmd(wd, epochs, **kw):
    cmd = [sys.executable, LAUNCHER, "--workdir", wd,
           "--epochs", str(epochs), "--nproc", "2"]
    for k, v in kw.items():
        cmd += [f"--{k}", str(v)]
    return cmd


def scenario_straggler(base):
    """Real gloo 2-process fleet: injected 150 ms boundary skew ->
    persistence verdict -> mitigation preempt -> rebalanced relaunch ->
    done, with a policy-off control run proving bit-identity."""
    wd = os.path.join(base, "straggler")
    # fresh workdir: a stale one-shot marker from a previous run would
    # silently disarm the injection and the scenario would hang waiting
    # for a mitigation that never comes
    shutil.rmtree(wd, ignore_errors=True)
    os.makedirs(wd, exist_ok=True)
    port = _free_port()
    epochs = 6
    cfg = SuperviseConfig(
        command=_fleet_cmd(
            wd, epochs, metrics_port=port, straggler_ms=150,
            straggler_pid=1,
            straggler_marker=os.path.join(wd, "straggler.marker"),
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        # bar 0.05s under the injected ~0.15s skew; K=3 of 5 boundaries
        # (the driver crosses ~2 flush boundaries per epoch at
        # print_freq=2, and the first publishes no skew — one-boundary
        # staleness — so the verdict lands around epoch 2 of 6, strictly
        # mid-run); generous grace covers SIGTERM -> collective preempt
        # decision -> fleet emergency save -> exit 75
        straggler_skew_secs=0.05, straggler_persist_k=3,
        straggler_window_n=5, straggler_mitigate=True,
        grace_secs=120.0, metrics_port=port,
    )
    sup, join = _run_supervisor(cfg)
    rc = join()
    rec, events = _record(
        "straggler", sup, rc, ["restart_rebalanced", "done"],
    )
    findings = [e for e in events if e["name"] == "straggler_finding"]
    verdicts = [e for e in events if e["name"] == "straggler_persistent"]
    mitigations = [e for e in events if e["name"] == "straggler_mitigation"]
    rec["straggler_findings"] = len(findings)
    rec["persistence_verdicts"] = len(verdicts)
    rec["mitigation_events"] = len(mitigations)
    # the relaunched fleet must have been LAUNCHED under the rebalance
    # hint, and the launcher must have seen it in its environment
    launches = [e["args"] for e in events if e["name"] == "launch"]
    rec["launch_shares"] = [la.get("share") for la in launches]
    result_path = os.path.join(wd, "fleet_result.json")
    result = json.load(open(result_path)) if os.path.exists(result_path) else {}
    rec["share_hint_carried"] = result.get("share_hint", "")
    hint_ok = bool(
        rec["share_hint_carried"]
        and rec["share_hint_carried"] in rec["launch_shares"]
    )
    # bit-identity: the SAME fleet, unsupervised and uninjected, must land
    # on the SAME final parameter digests — mitigation (preempt, resume,
    # rebalance hint) changes where work runs, never what is computed
    wd_c = os.path.join(base, "straggler_control")
    shutil.rmtree(wd_c, ignore_errors=True)
    os.makedirs(wd_c, exist_ok=True)
    with open(os.path.join(wd_c, "control.log"), "w") as log:
        subprocess.run(
            _fleet_cmd(wd_c, epochs), check=True, cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, timeout=WAIT_S,
        )
    control = json.load(open(os.path.join(wd_c, "fleet_result.json")))
    digests = [w.get("digest") for w in result.get("workers", [])]
    control_digests = [w.get("digest") for w in control["workers"]]
    rec["digests"] = digests
    rec["control_digests"] = control_digests
    rec["bit_identical"] = bool(digests and digests == control_digests)
    rec["ok"] = bool(
        rec["ok"] and rc == 0 and findings and verdicts
        and len(mitigations) >= 2   # phase=preempt AND phase=decided
        and hint_ok and rec["bit_identical"]
    )
    return rec


def scenario_chaos(base):
    """The composed run: straggler skew + SIGKILL + injected health
    collapse (policy warn) in one supervised lifetime, landed green."""
    wd = os.path.join(base, "chaos")
    shutil.rmtree(wd, ignore_errors=True)  # stale marker = disarmed fault
    os.makedirs(wd, exist_ok=True)
    port = _free_port()
    cfg = SuperviseConfig(
        # the victim straggles 150 ms per boundary (one-shot marker: the
        # mitigation relaunch runs clean) AND its health thresholds are
        # impossible — but under --health_policy warn collapse only
        # alarms, it never aborts, so the supervisor must keep the run
        # alive through all three injected failures
        command=_victim_cmd(
            wd, epochs=6, trial="chaos", save_freq=1, metrics_port=port,
            straggler_ms=150,
            straggler_marker=os.path.join(wd, "straggler.marker"),
            fault="collapse", health_freq=2, health_policy="warn",
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        straggler_skew_secs=0.05, straggler_persist_k=3,
        straggler_window_n=5, straggler_mitigate=True,
        grace_secs=120.0, metrics_port=port,
    )
    sup, join = _run_supervisor(cfg)
    # first life: wait for the mitigation to have actuated (decision 1
    # recorded, relaunched child alive), then SIGKILL the SECOND life —
    # the mitigated fleet must also survive an unrelated hard death
    first_pid = _wait_for(
        lambda: sup.child and sup.child.pid, "first child pid"
    )
    def relaunched():
        if not sup.decisions:
            return None
        if sup.decisions[0].action != "restart_rebalanced":
            return None
        child = sup.child
        if child and child.pid != first_pid and child.poll() is None:
            return child.pid
        return None
    second_pid = _wait_for(relaunched, "rebalanced relaunch")
    os.kill(second_pid, signal.SIGKILL)
    rc = join()
    rec, events = _record(
        "chaos", sup, rc,
        ["restart_rebalanced", "backoff_restart", "done"],
        detail={"killed_pid": second_pid},
    )
    alarms = [
        e for e in events
        if e["name"] == "trainer_event"
        and e.get("args", {}).get("event") == "health_alarm"
    ]
    mitigations = [e for e in events if e["name"] == "straggler_mitigation"]
    rec["health_alarms_observed"] = len(alarms)
    rec["mitigation_events"] = len(mitigations)
    rec["ok"] = bool(
        rec["ok"] and rc == 0 and alarms and len(mitigations) >= 2
    )
    return rec


SCENARIOS = {
    "sigkill": scenario_sigkill,
    "stall": scenario_stall,
    "collapse": scenario_collapse,
    "preempt_resize": scenario_preempt_resize,
    "straggler": scenario_straggler,
    "chaos": scenario_chaos,
}
# the straggler-mitigation scenarios land in their own artifact (ratchet's
# chaos_matrix config) so the r11 supervisor artifact stays byte-stable
CHAOS_NAMES = ("straggler", "chaos")
CHAOS_SCHEMA = "chaos_matrix/v1"


def run_matrix(base, names):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.abspath(os.path.join(REPO, ".jax_cache")),
    )
    scenarios = {}
    for name in names:
        rec = SCENARIOS[name](base)
        print(json.dumps(rec), flush=True)
        scenarios[name] = rec
    return scenarios


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir",
                    default=os.path.join(REPO, "work_space", "supervisor_matrix"))
    ap.add_argument("--json", default="",
                    help="supervisor_matrix artifact (the four r11 "
                         "scenarios)")
    ap.add_argument("--chaos_json", default="",
                    help="chaos_matrix artifact (the straggler/chaos "
                         "scenarios)")
    ap.add_argument("--scenarios", nargs="+", default=list(SCENARIOS),
                    choices=list(SCENARIOS))
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    # fresh-artifact convention (scripts/ratchet.py): a failed producer
    # must never leave a stale green artifact for the gate to re-verify
    for path in (args.json, args.chaos_json):
        if path and os.path.exists(path):
            os.remove(path)
    scenarios = run_matrix(args.workdir, args.scenarios)
    ok = all(r["ok"] for r in scenarios.values())
    print(json.dumps({"metric": "supervisor_matrix", "ok": ok}))
    legacy = {k: v for k, v in scenarios.items() if k not in CHAOS_NAMES}
    chaos = {k: v for k, v in scenarios.items() if k in CHAOS_NAMES}
    if args.json and legacy:
        with open(args.json, "w") as f:
            json.dump({
                "metric": "supervisor_matrix",
                "victim": os.path.relpath(VICTIM, REPO),
                "scenarios": legacy,
                "ok": all(r["ok"] for r in legacy.values()),
            }, f, indent=1)
    if args.chaos_json and chaos:
        with open(args.chaos_json, "w") as f:
            json.dump({
                "metric": "chaos_matrix",
                "schema": CHAOS_SCHEMA,
                "victim": os.path.relpath(VICTIM, REPO),
                "launcher": os.path.relpath(LAUNCHER, REPO),
                "scenarios": chaos,
                "ok": all(r["ok"] for r in chaos.values()),
            }, f, indent=1)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
