#!/usr/bin/env python
"""The supervisor scenario matrix — the REAL supervisor babysitting the REAL
driver through the four failure shapes, producing the committed evidence
artifact ``docs/evidence/supervisor_r11.json`` that ``scripts/ratchet.py``'s
``supervisor_gate`` verifies.

Scenarios (each in its own workdir; the victim is
``scripts/supervisor_victim.py`` — a 7-step/epoch synthetic pretrain with
one-shot injectable faults):

- ``sigkill``: SIGKILL lands mid-run (no grace, torn async save possible);
  the supervisor must observe the signal death, restart with ``--resume``
  (resolution picks the newest COMPLETE save), and the job must finish —
  decisions ``backoff_restart`` then ``done``;
- ``stall``: the victim's main thread wedges at a flush boundary (and
  absorbs SIGTERM via the preempt flag, like a dead collective); the
  supervisor must see liveness die — the scraped
  ``train_last_boundary_age_seconds`` climbing past the deadline, plus the
  in-child watchdog's stall dump in the run dir — kill through the grace
  escalation, and resume;
- ``collapse``: impossible health thresholds force a representation-health
  abort (typed exit 3) under ``--health_policy abort``; the supervisor must
  GIVE UP (collapse lives in the weights — docs/RESILIENCE.md precedence),
  exiting with the child's code;
- ``preempt_resize``: a ``resize_request`` file arrives mid-run; the
  supervisor gracefully preempts (SIGTERM -> emergency save -> exit 75)
  and relaunches ``--resume`` onto the new virtual-mesh device count —
  the elastic-resume proof (mesh-shape-agnostic restore,
  utils/checkpoint.py) driven end to end.

Each scenario prints one JSON line and lands in the artifact with its
decision sequence, exit code, and the supervisor events file it came from.

Usage:
    python scripts/supervisor_matrix.py --json docs/evidence/supervisor_r11.json
    python scripts/supervisor_matrix.py --scenarios sigkill stall
"""

import argparse
import json
import os
import signal
import socket
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from simclr_pytorch_distributed_tpu.supervise import (  # noqa: E402
    SuperviseConfig,
    Supervisor,
)
from simclr_pytorch_distributed_tpu.supervise.launch import (  # noqa: E402
    find_resume_dir,
)

VICTIM = os.path.join(REPO, "scripts", "supervisor_victim.py")
WAIT_S = 600.0  # per-wait ceiling (cold sharded compiles on a slow host)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_for(predicate, what: str, timeout_s: float = WAIT_S):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.25)
    raise RuntimeError(f"timeout waiting for {what}")


def _run_supervisor(cfg: SuperviseConfig):
    """Run the supervisor on a thread; returns (supervisor, join->rc)."""
    sup = Supervisor(cfg)
    box = {}

    def target():
        box["rc"] = sup.run()

    t = threading.Thread(target=target, name="supervisor", daemon=True)
    t.start()

    def join(timeout_s: float = WAIT_S) -> int:
        t.join(timeout_s)
        if t.is_alive():
            raise RuntimeError("supervisor did not finish")
        return box["rc"]

    return sup, join


def _events(sup: Supervisor):
    return [json.loads(line) for line in open(sup.recorder._path)]


def _record(name, sup, rc, expect_actions, detail=None):
    actions = [d.action for d in sup.decisions]
    events = _events(sup)
    rec = {
        "scenario": name,
        "rc": rc,
        "decisions": actions,
        "expected_decisions": list(expect_actions),
        "attempts": sum(1 for e in events if e["name"] == "launch"),
        "events_file": os.path.relpath(sup.recorder._path, REPO),
        "n_events": len(events),
        "ok": actions == list(expect_actions),
        **(detail or {}),
    }
    return rec, events


def _victim_cmd(workdir, **kw):
    cmd = [sys.executable, VICTIM, "--workdir", workdir]
    for k, v in kw.items():
        cmd += [f"--{k}", str(v)]
    return cmd


def _wait_for_checkpoint(workdir, name="ckpt_epoch_1"):
    def check():
        run_dir = find_resume_dir(workdir)
        if run_dir and os.path.exists(os.path.join(run_dir, name, "meta.json")):
            return run_dir
        return None

    return _wait_for(check, f"{name} in {workdir}")


def scenario_sigkill(base):
    # ckpt_epoch_1's async meta stamps at epoch 2's save drain, so the kill
    # lands around epoch 3 of 4 — strictly mid-run, with a complete save on
    # disk for the resume (the fault-harness kill9 geometry)
    wd = os.path.join(base, "sigkill")
    cfg = SuperviseConfig(
        command=_victim_cmd(wd, epochs=4, trial="k9", save_freq=1),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
    )
    sup, join = _run_supervisor(cfg)
    _wait_for_checkpoint(wd)
    pid = sup.child.pid
    os.kill(pid, signal.SIGKILL)
    rc = join()
    rec, _ = _record(
        "sigkill", sup, rc, ["backoff_restart", "done"],
        detail={"killed_pid": pid},
    )
    rec["ok"] = rec["ok"] and rc == 0
    return rec


def scenario_stall(base):
    wd = os.path.join(base, "stall")
    os.makedirs(wd, exist_ok=True)
    port = _free_port()
    cfg = SuperviseConfig(
        # 7 requested_global calls per complete epoch (6 mid-epoch
        # boundaries + the epoch edge): fault_step=16 wedges the main
        # thread at epoch 3 boundary 2 — AFTER ckpt_epoch_1's meta stamped
        # (epoch 2's save drain), so the post-kill resume has a complete
        # save to resolve. watchdog_secs must exceed the child's STARTUP
        # (jax import + first-step trace) — the watchdog arms at
        # construction, and a pre-first-boundary false dump would be read
        # as a stall verdict (the supervisor kills on the child's own dump
        # by design); 15s clears a warm-cache startup severalfold while the
        # real stall, which never beats again, still trips it
        command=_victim_cmd(
            wd, epochs=3, trial="stall", save_freq=1, fault="stall",
            fault_step=16, fault_marker=os.path.join(wd, "stall.marker"),
            metrics_port=port, watchdog_secs=15,
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        stall_secs=25.0, grace_secs=3.0, metrics_port=port,
    )
    sup, join = _run_supervisor(cfg)
    rc = join()
    rec, events = _record("stall", sup, rc, ["backoff_restart", "done"])
    stall_events = [e for e in events if e["name"] == "liveness_stall"]
    dump_events = [e for e in events if e["name"] == "stall_dump_observed"]
    rec["liveness_stalls"] = len(stall_events)
    rec["watchdog_dumps_observed"] = len(dump_events)
    # the decision must have come from a LIVENESS verdict, and the
    # in-child watchdog's artifact must have been surfaced too
    rec["ok"] = bool(rec["ok"] and rc == 0 and stall_events and dump_events)
    return rec


def scenario_collapse(base):
    wd = os.path.join(base, "collapse")
    cfg = SuperviseConfig(
        command=_victim_cmd(
            wd, epochs=1, trial="collapse", fault="collapse",
            health_freq=2, health_policy="abort",
        ),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
    )
    sup, join = _run_supervisor(cfg)
    rc = join()
    rec, events = _record("collapse", sup, rc, ["give_up"])
    alarms = [
        e for e in events
        if e["name"] == "trainer_event"
        and e.get("args", {}).get("event") == "health_alarm"
    ]
    rec["health_alarms_observed"] = len(alarms)
    rec["ok"] = bool(rec["ok"] and rc == 3 and alarms)
    return rec


def scenario_preempt_resize(base, devices_before=8, devices_after=4):
    wd = os.path.join(base, "preempt_resize")
    # epochs=4: the resize request (written once ckpt_epoch_1's meta is
    # stamped, i.e. ~epoch 3) catches the child strictly mid-run; the
    # generous grace covers the SIGTERM -> flush-boundary -> emergency-save
    # exit-75 sequence on a slow host
    cfg = SuperviseConfig(
        command=_victim_cmd(wd, epochs=4, trial="resize", save_freq=1),
        workdir=wd, max_restarts=3, backoff_base_s=0.2, poll_s=0.25,
        grace_secs=120.0, devices=devices_before,
    )
    sup, join = _run_supervisor(cfg)
    _wait_for_checkpoint(wd)
    with open(os.path.join(sup.supervise_dir, "resize_request"), "w") as f:
        f.write(str(devices_after))
    rc = join()
    rec, events = _record(
        "preempt_resize", sup, rc, ["restart_resized", "done"],
        detail={"devices_before": devices_before,
                "devices_after": devices_after},
    )
    launches = [e["args"] for e in events if e["name"] == "launch"]
    rec["launch_devices"] = [la.get("devices") for la in launches]
    resized = [la for la in launches if la.get("devices") == devices_after]
    # the relaunch must land on the NEW topology AND resume the old run
    rec["resumed_resized"] = bool(resized and resized[0].get("resume"))
    rec["ok"] = bool(rec["ok"] and rc == 0 and rec["resumed_resized"])
    return rec


SCENARIOS = {
    "sigkill": scenario_sigkill,
    "stall": scenario_stall,
    "collapse": scenario_collapse,
    "preempt_resize": scenario_preempt_resize,
}


def run_matrix(base, names):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.abspath(os.path.join(REPO, ".jax_cache")),
    )
    scenarios = {}
    for name in names:
        rec = SCENARIOS[name](base)
        print(json.dumps(rec), flush=True)
        scenarios[name] = rec
    return {
        "metric": "supervisor_matrix",
        "victim": os.path.relpath(VICTIM, REPO),
        "scenarios": scenarios,
        "ok": all(r["ok"] for r in scenarios.values()),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir",
                    default=os.path.join(REPO, "work_space", "supervisor_matrix"))
    ap.add_argument("--json", default="")
    ap.add_argument("--scenarios", nargs="+", default=list(SCENARIOS),
                    choices=list(SCENARIOS))
    args = ap.parse_args()
    os.makedirs(args.workdir, exist_ok=True)
    artifact = run_matrix(args.workdir, args.scenarios)
    print(json.dumps({"metric": "supervisor_matrix", "ok": artifact["ok"]}))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(artifact, f, indent=1)
    sys.exit(0 if artifact["ok"] else 1)


if __name__ == "__main__":
    main()
