#!/usr/bin/env python
"""Does the background telemetry executor remove the per-window flush stall?

docs/PERF.md round 5 measured each MetricBuffer flush as a synchronous
batched D2H costing ~110 ms on the tunneled link (~5.5 ms/step at the
recipe's ``print_freq 20``). The zero-sync path (device-side metric ring +
utils/telemetry.py background flush) claims to take that off the dispatch
thread. This script MEASURES it on a CPU proxy with an injected transfer
delay standing in for the slow link, rather than assuming it:

- both arms run the SAME compiled ring-mode fused update (one trace, shared
  by both — perfectly paired work);
- the ``sync`` arm runs every window job inline (``--telemetry sync``
  semantics: the dispatch thread eats D2H + delay);
- the ``async`` arm hands windows to the telemetry thread (``--telemetry
  async``) and only waits at the final ``drain()``;
- the injected delay wraps the ring's injectable ``device_get``
  (``--delay_ms``), the same hook the transfer-count tests instrument;
- arm order is ABBA within every round (PR 3's serve-sweep convention:
  machine drift moves medians more than the treatment), and the honest-sync
  rule holds — every timed arm ends by DRAINING the ring, so the fetched
  metric values are computed scalars that cannot exist until the steps ran.

Expectation: sync_ms_per_step - async_ms_per_step ~= delay/steps_per_window
(the async arm still pays the LAST window's delay at drain, amortized over
the whole arm). The committed artifact is docs/evidence/flush_ab_r6.json;
the chip expectation derived from it lives in docs/PERF.md ("Zero-sync
telemetry").

Usage: python scripts/flush_ab.py [--smoke] [--delay_ms N] [--json OUT]
"""

import argparse
import json
import os
import statistics
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.ops.metrics import MetricRing  # noqa: E402
from simclr_pytorch_distributed_tpu.parallel.mesh import (  # noqa: E402
    create_mesh,
    replicated_sharding,
    shard_host_batch,
)
from simclr_pytorch_distributed_tpu.train.supcon_step import (  # noqa: E402
    METRIC_KEYS,
)
from simclr_pytorch_distributed_tpu.utils.telemetry import (  # noqa: E402
    TelemetrySession,
)

ARM_ORDER = ("sync", "async", "async", "sync")  # ABBA within every round


def build_output(device, delay_ms, window, windows, rounds_records):
    """Assemble the committed-artifact JSON from per-round arm timings.

    ``rounds_records``: one dict per round, ``{"sync": [ms_per_step, ...],
    "async": [...]}`` — two measurements per arm per round (the ABBA order).
    Pure so tests pin the schema without running the measurement.
    """
    all_sync = [v for r in rounds_records for v in r["sync"]]
    all_async = [v for r in rounds_records for v in r["async"]]
    sync_ms = statistics.median(all_sync)
    async_ms = statistics.median(all_async)
    return {
        "metric": "flush_ab_ms_per_step",
        "delay_ms": delay_ms,
        "window": window,
        "windows_per_arm": windows,
        "arm_order": "ABBA per round: " + ",".join(ARM_ORDER),
        "runs": rounds_records,
        "summary": {
            "sync_ms_per_step": round(sync_ms, 2),
            "async_ms_per_step": round(async_ms, 2),
            "stall_removed_ms_per_window": round((sync_ms - async_ms) * window, 1),
            "speedup": round(sync_ms / async_ms, 3) if async_ms > 0 else None,
        },
        "device": device,
        "note": (
            "paired CPU-proxy A/B: same compiled ring-mode update both arms; "
            "injected device_get delay stands in for the slow D2H link; each "
            "arm ends with drain() so every timed value is a computed scalar"
        ),
    }


def main(argv=None):
    def positive_int(s):
        v = int(s)
        if v < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return v

    def nonneg_float(s):
        v = float(s)
        if v < 0:
            raise argparse.ArgumentTypeError(f"must be >= 0, got {v}")
        return v

    ap = argparse.ArgumentParser()
    ap.add_argument("--delay_ms", type=nonneg_float, default=None,
                    help="injected per-flush transfer delay; default 110 ms "
                         "(the round-5 measured tunneled-link flush cost), "
                         "400 ms under --smoke")
    ap.add_argument("--window", type=positive_int, default=None,
                    help="steps per flush window (the recipe's print_freq); "
                         "default 20, 10 under --smoke")
    ap.add_argument("--windows", type=positive_int, default=None,
                    help="windows per arm; default 4, 5 under --smoke")
    ap.add_argument("--rounds", type=positive_int, default=2,
                    help="ABBA rounds (2 measurements per arm per round)")
    ap.add_argument("--batch", type=positive_int, default=None,
                    help="default 64, 8 under --smoke")
    ap.add_argument("--size", type=positive_int, default=None,
                    help="default 16, 8 under --smoke")
    ap.add_argument("--model", default="resnet10")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CPU config (8px, 10-step windows) for tests "
                         "and the committed-artifact run")
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)
    # --smoke picks the CPU-proxy shape (tuned so the injected stall is
    # comparable to the tiny-model compute: the effect must clear single-core
    # timer noise, ~±5 ms/step, by a wide margin, not hide inside it) but
    # only for flags the caller left unset — an explicit --delay_ms sweep
    # must not be silently overridden.
    smoke_defaults = dict(size=8, batch=8, window=10, windows=5,
                          delay_ms=400.0)
    full_defaults = dict(size=16, batch=64, window=20, windows=4,
                         delay_ms=110.0)
    for k, v in (smoke_defaults if args.smoke else full_defaults).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    import jax.numpy as jnp

    from simclr_pytorch_distributed_tpu.models import SupConResNet
    from simclr_pytorch_distributed_tpu.ops.augment import AugmentConfig
    from simclr_pytorch_distributed_tpu.ops.schedules import make_lr_schedule
    from simclr_pytorch_distributed_tpu.train.state import (
        create_train_state,
        make_optimizer,
    )
    from simclr_pytorch_distributed_tpu.train.supcon import make_fused_update
    from simclr_pytorch_distributed_tpu.train.supcon_step import SupConStepConfig

    mesh = create_mesh(devices=jax.devices()[:1])
    model = SupConResNet(model_name=args.model, head="mlp", feat_dim=128)
    schedule = make_lr_schedule(learning_rate=0.1, epochs=10,
                                steps_per_epoch=100, cosine=True)
    tx = make_optimizer(schedule, momentum=0.9, weight_decay=1e-4)
    state = create_train_state(
        model, tx, jax.random.key(0),
        jnp.zeros((2, args.size, args.size, 3), jnp.float32),
    )
    step_cfg = SupConStepConfig(
        method="SimCLR", temperature=0.5, epochs=10, steps_per_epoch=100,
        grad_div=1.0, loss_impl="dense",
    )
    # one trace shared by BOTH arms: write-side columns come from this ring,
    # flush-side rings below only need the same (window, keys)
    ring_spec = MetricRing(args.window, METRIC_KEYS)
    update = make_fused_update(
        model, tx, schedule, step_cfg, AugmentConfig(size=args.size), mesh,
        state, metric_ring=ring_spec,
    )

    rng = np.random.default_rng(0)
    images = rng.integers(
        0, 256, size=(args.batch, args.size, args.size, 3), dtype=np.uint8
    )
    labels = rng.integers(0, 10, size=(args.batch,)).astype(np.int32)
    sh_images, sh_labels = shard_host_batch((images, labels), mesh)
    base_key = jax.random.key(42)
    repl = replicated_sharding(mesh)
    delay_s = args.delay_ms / 1e3

    def delayed_get(x):
        time.sleep(delay_s)
        return jax.device_get(x)

    gstep = [int(state.step)]

    def run_arm(mode, state):
        session = TelemetrySession(
            args.window, METRIC_KEYS, mode, device_get=delayed_get
        )
        sink = []
        ring_buf = session.init_buffer(repl)
        t0 = time.perf_counter()
        for w in range(args.windows):
            for _ in range(args.window):
                state, ring_buf = update(
                    state, ring_buf, sh_images, sh_labels, base_key
                )
                session.append(w, gstep[0])
                gstep[0] += 1
            session.submit_window(ring_buf, sink.extend)
        session.drain()  # computed-scalar materialization: the honest sync
        dt = time.perf_counter() - t0
        session.close()
        assert len(sink) == args.windows * args.window
        assert all(np.isfinite(m["loss"]) for _, m in sink)
        return state, dt * 1e3 / (args.windows * args.window)

    # warmup: compile + ONE FULL DISCARDED ARM (PR 3's discarded-warm-window
    # convention) — the first measured windows otherwise carry allocator /
    # code-cache settling that lands entirely on whichever arm runs first
    state, warm_ms = run_arm("sync", state)
    print(json.dumps({"warmup_discarded_ms_per_step": round(warm_ms, 2)}),
          flush=True)

    rounds_records = []
    for rnd in range(args.rounds):
        record = {"sync": [], "async": []}
        for mode in ARM_ORDER:
            state, ms = run_arm(mode, state)
            record[mode].append(round(ms, 2))
            print(json.dumps({"round": rnd, "arm": mode,
                              "ms_per_step": round(ms, 2)}), flush=True)
        rounds_records.append(record)

    out = build_output(
        jax.devices()[0].device_kind, args.delay_ms, args.window,
        args.windows, rounds_records,
    )
    print(json.dumps(out))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
