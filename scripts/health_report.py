#!/usr/bin/env python
"""Per-run training-health timeline + anomaly findings from a flight-recorder
``events.jsonl``.

The metric ring streams the on-device representation diagnostics
(train/supcon_step.HEALTH_METRIC_KEYS + the online-probe columns) to the
host, and the :class:`guard.HealthMonitor` summarizes each flush window into
one ``health_window`` event (the window means) plus ``health_alarm`` events
for verdicts — so the recorder's jsonl IS the durable health metric stream,
and this script is its post-hoc reader: it rebuilds the per-window timeline,
summarizes each metric's trajectory (first/last/min/max), surfaces findings
(alarms, the collapse signature on the final window, guard events like NaN
rollbacks and preemptions), and writes a JSON artifact — the committed
``docs/evidence/health_report_r*.json`` convention, and the ``health_report``
config in ``scripts/ratchet.py``'s default gate list (which binds on the
report's internal consistency and zero alarms on the healthy smoke;
the probe-accuracy claim is CPU-calibrated and pass-skips elsewhere).

Usage:
    python scripts/health_report.py --events <run_dir>/events.jsonl \
        [--json out.json]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.utils import tracing  # noqa: E402
from simclr_pytorch_distributed_tpu.utils.guard import (  # noqa: E402
    thresholds_for_recipe,
)

SCHEMA = "health_report/v1"

# every health_window event must carry these (the ring columns are fixed per
# run, so a missing key means the stream was torn or produced by another tool)
REQUIRED_HEALTH_KEYS = (
    "health_align", "health_con_top1", "health_eff_rank",
    "health_grad_norm", "health_neg_max", "health_neg_mean", "health_unif",
)

# Final-window collapse signature (report-only; the LIVE verdicts are the
# HealthMonitor's — read off guard.thresholds_for_recipe, not copied, so
# the offline reader cannot drift from the live detector). RECIPE-AWARE:
# the per-recipe bars (guard.RECIPE_HEALTH_THRESHOLDS — the negative-free
# recipes run under a raised eff-rank bar) are resolved from the run's
# recorded ``run_recipe`` event (train/supcon.py stamps it at startup) or
# the --recipe override, so an offline reader reaches the SAME verdict the
# live monitor would; pre-recipe streams resolve to the defaults.


def recipe_from_events(events) -> "str | None":
    """The run's recorded recipe (the driver's ``run_recipe`` guard event),
    or ``None`` for pre-recipe / probe / CE streams."""
    for e in events:
        if e.get("name") == "run_recipe":
            return e.get("args", {}).get("recipe")
    return None

# guard events that are findings in themselves (trace_report's convention)
EVENT_FLAGS = {
    "health_alarm": "collapse/divergence detector fired",
    "stall_detected": "stall watchdog fired (see stall_dump_* artifacts)",
    "nan_rollback": "NaN rollback(s) recorded",
    "preempt_exit": "run ended by preemption",
    "flush_failure": "telemetry flush failure observed",
}


def session_paths(path):
    """The files one ``--events`` argument selects.

    The BASE session file (``events.jsonl`` / ``events_pN.jsonl``) expands
    to the process's whole session family — a resumed run (the exit-75
    relaunch lands in the same save_folder) rotates to ``events_r2.jsonl``,
    ``events_r3.jsonl``, ... (utils/tracing.run_paths), and reading only
    the first file silently truncated a resumed run's health timeline at
    the first preemption. An EXPLICIT rotated file (``events_r2.jsonl``)
    selects exactly that session: asking for one session must not be
    silently overridden with the whole family."""
    m = tracing.EVENTS_FILE_RE.match(os.path.basename(path))
    if m and not m.group(3):
        return tracing.session_files_for(path)
    return [path]


def load_events(path):
    """The selected session(s), concatenated in session order (see
    :func:`session_paths`). Health windows key on the GLOBAL step
    (restored across resumes), not the per-session clock, so
    concatenation keeps the timeline monotone and the consistency checks
    meaningful. Each file is read through the shared torn-line-tolerant
    loader (tracing.parse_jsonl): the half-written final line a SIGKILL
    leaves is exactly the run this report exists to diagnose."""
    events = []
    for session_path in session_paths(path):
        events.extend(tracing.load_events_jsonl(session_path))
    return events


def build_report(events, recipe=None):
    """The health report (pure — tests/test_health.py drives it on synthetic
    event lists). ``recipe`` overrides the recipe recorded in the stream;
    the resolved name selects the per-recipe collapse-signature bars
    (guard.thresholds_for_recipe — the live monitor's own table)."""
    if not events:
        raise ValueError("no events: recorder off or empty run?")
    recipe = recipe if recipe is not None else recipe_from_events(events)
    bars = thresholds_for_recipe(recipe)
    windows = [
        e.get("args", {}) for e in events
        if e.get("name") == "health_window" and e.get("track") == "health"
    ]
    timeline = [w for w in windows if "step" in w]
    steps = [int(w["step"]) for w in timeline]
    keys = sorted(set().union(*(w.keys() for w in timeline)) - {"step"}) if timeline else []

    series = {}
    for k in keys:
        vals = [(int(w["step"]), float(w[k])) for w in timeline if k in w]
        if not vals:
            continue
        nums = [v for _, v in vals]
        series[k] = {
            "first": nums[0], "last": nums[-1],
            "min": min(nums), "max": max(nums), "n": len(nums),
        }

    findings = []
    alarms = [
        dict(e.get("args", {}), name=e["name"]) for e in events
        if e.get("name") == "health_alarm"
    ]
    event_counts = {}
    for e in events:
        if e.get("ph") == "i" and e.get("name") in EVENT_FLAGS:
            event_counts[e["name"]] = event_counts.get(e["name"], 0) + 1
    for name, count in sorted(event_counts.items()):
        findings.append({"kind": name, "flag": f"{EVENT_FLAGS[name]} (x{count})"})
    if timeline:
        last = timeline[-1]
        if float(last.get("health_eff_rank", float("inf"))) < bars.eff_rank_min:
            findings.append({
                "kind": "collapse_signature",
                "flag": f"final-window effective rank "
                        f"{last['health_eff_rank']:.3g} < "
                        f"{bars.eff_rank_min:g}"
                        + (f" (recipe {recipe} bar)" if recipe else ""),
            })
        if (float(last.get("health_align", 0.0)) > bars.align_max
                and float(last.get("health_neg_mean", 0.0)) > bars.neg_mean_max):
            findings.append({
                "kind": "collapse_signature",
                "flag": "final-window positives AND negatives ~1",
            })

    probe = None
    if any(k.startswith("probe_") for k in keys):
        probe = {
            "first_top1": series["probe_top1"]["first"],
            "last_top1": series["probe_top1"]["last"],
            "best_top1": series["probe_top1"]["max"],
            "windows": series["probe_top1"]["n"],
        }

    if timeline:
        missing = sorted(
            k for k in REQUIRED_HEALTH_KEYS
            if any(k not in w for w in timeline)
        )
    else:
        missing = list(REQUIRED_HEALTH_KEYS)
    monotone_ok = all(a <= b for a, b in zip(steps, steps[1:]))
    consistency = {
        "n_windows": len(timeline),
        "monotone_ok": bool(monotone_ok),
        "missing_keys": missing,
        # the gate bit: a non-empty, monotone timeline in which every
        # window carries the full health column set — i.e. the on-device
        # diagnostics really streamed through the ring to the recorder
        "ok": bool(timeline) and monotone_ok and not missing,
    }
    return {
        "timeline": timeline,
        "series": series,
        "probe": probe,
        "alarms": alarms,
        "findings": findings,
        "consistency": consistency,
        "n_events": len(events),
        # the verdict's provenance: which recipe's bars judged the stream
        "recipe": recipe,
        "thresholds": {
            "eff_rank_min": bars.eff_rank_min,
            "align_max": bars.align_max,
            "neg_mean_max": bars.neg_mean_max,
        },
    }


def render_table(report):
    lines = []
    rows = [("metric", "first", "last", "min", "max", "n")]
    for name, s in sorted(report["series"].items()):
        rows.append((
            name, f"{s['first']:.4g}", f"{s['last']:.4g}",
            f"{s['min']:.4g}", f"{s['max']:.4g}", str(s["n"]),
        ))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    lines += [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths))
        for row in rows
    ]
    if len(lines) > 1:
        lines.insert(1, "-" * len(lines[0]))
    for f in report["findings"]:
        lines.append(f"FINDING [{f['kind']}]: {f['flag']}")
    if report["probe"]:
        p = report["probe"]
        lines.append(
            f"online probe top-1: {p['first_top1']:.2f} -> "
            f"{p['last_top1']:.2f} (best {p['best_top1']:.2f} over "
            f"{p['windows']} windows)"
        )
    if not report["consistency"]["ok"]:
        lines.append(
            "CONSISTENCY: FAILED (empty/torn/non-monotone health stream: "
            f"{report['consistency']})"
        )
    return "\n".join(lines)


def build_output(events_path, report, device, session_files=None):
    """The committed artifact (pure; schema pinned by tests). ``device`` is
    the analyzing host's jax backend — the ratchet gate runs the trainer and
    this report on the same box, and uses it to scope the CPU-calibrated
    probe-accuracy claim. ``session_files`` records the files ACTUALLY
    read (a base ``--events`` expands to the whole rotated-session
    family), so the artifact's provenance never understates its input."""
    out = {
        "schema": SCHEMA, "events": events_path, "device": device,
        "report": report,
    }
    if session_files is not None:
        out["session_files"] = [os.path.basename(p) for p in session_files]
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", required=True,
                    help="a flight-recorder events.jsonl (run dir artifact)")
    ap.add_argument("--json", default="",
                    help="write the health-report artifact here")
    ap.add_argument("--recipe", default=None,
                    help="override the recorded recipe when selecting the "
                        "per-recipe collapse-signature bars (default: the "
                        "stream's run_recipe event)")
    args = ap.parse_args(argv)
    if args.recipe is not None:
        from simclr_pytorch_distributed_tpu.utils.guard import (
            RECIPE_HEALTH_THRESHOLDS,
        )

        # a typo'd override would silently judge the stream by the DEFAULT
        # bars while stamping the bogus name as verdict provenance —
        # exactly the live/offline drift the recipe-aware report prevents
        if args.recipe not in RECIPE_HEALTH_THRESHOLDS:
            ap.error(
                f"--recipe {args.recipe!r} is not a known recipe "
                f"(choose from {sorted(RECIPE_HEALTH_THRESHOLDS)})"
            )

    report = build_report(load_events(args.events), recipe=args.recipe)
    print(render_table(report))
    if args.json:
        import jax  # lazy: the report itself is pure json-over-json

        with open(args.json, "w") as f:
            json.dump(
                build_output(
                    args.events, report, jax.default_backend(),
                    session_files=session_paths(args.events),
                ),
                f, indent=1,
            )
        print(f"wrote {args.json}")
    return 0 if report["consistency"]["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
