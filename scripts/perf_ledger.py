#!/usr/bin/env python
"""The longitudinal perf ledger: every bench result, appended forever.

``bench.py`` measures one number per run and ``vs_baseline`` compares it
against ONE frozen headline — there is no history, so a slow drift (1% a
revision for ten revisions) is invisible to the gate until it crosses the
single 95% bar, and when it does there is nothing to bisect against. This
module is the history: ``docs/perf_ledger.jsonl`` holds one schema-pinned
record per bench run — git revision, a workload FINGERPRINT (stage,
config string, global batch, device kind, chips — the identity under
which throughput numbers are comparable at all), imgs/s/chip, step ms,
the clock-suspect verdict, and optionally the trace-report phase shares —
so perf drift becomes attributable to a REVISION (which commit moved the
number) and a PHASE (which part of the step absorbed the time).

Regression detection (:func:`detect_regression`, pure) follows the bench
gate's conventions: the latest record of each fingerprint group is
compared against the MEDIAN of its trailing same-fingerprint window;
clock-suspect runs are excluded from BOTH sides (a glitched number must
neither set nor trip the bar); groups without a sufficient clean trailing
window pass-skip with the reason on record (a new workload/device has no
history to regress against). ``scripts/ratchet.py``'s ``perf_ledger``
config runs the same pure verdict over the committed ledger.

Usage:
    python bench.py --ledger                       # measure + append
    python scripts/perf_ledger.py append --bench-json bench.log \
        [--phases trace_report.json] [--ledger docs/perf_ledger.jsonl]
    python scripts/perf_ledger.py check [--ledger docs/perf_ledger.jsonl] \
        [--json out.json]
"""

import argparse
import hashlib
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SCHEMA = "perf_ledger/v1"
CHECK_SCHEMA = "perf_ledger_check/v1"  # the `check` CLI's own artifact
DEFAULT_LEDGER = os.path.join("docs", "perf_ledger.jsonl")
# every record must carry these (the pinned schema the ratchet gate checks)
REQUIRED_KEYS = (
    "schema", "ts_unix", "git_rev", "fingerprint", "stage", "device_kind",
    "chips", "imgs_per_sec_per_chip", "step_ms", "clock_suspect",
)
# regression bar: latest vs the trailing-window median, the ratchet bench
# gate's fraction (a ledger regression should fail exactly where the bench
# bar would, just against the measured history instead of one frozen number)
REGRESSION_FRACTION = 0.95
TRAIL_WINDOW = 5      # trailing same-fingerprint records consulted
MIN_TRAIL = 2         # fewer than this and the bar cannot bind


def git_rev(repo=REPO):
    """Short HEAD revision (+ '-dirty' when the tree is modified), or
    'unknown' outside a usable git checkout — a ledger record must never
    fail to append over provenance."""
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=repo,
            capture_output=True, text=True, timeout=10,
        )
        if rev.returncode != 0:
            return "unknown"
        out = rev.stdout.strip()
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=repo,
            capture_output=True, text=True, timeout=10,
        )
        if dirty.returncode == 0 and dirty.stdout.strip():
            out += "-dirty"
        return out
    except (OSError, subprocess.SubprocessError):
        return "unknown"


def fingerprint_for(stage, detail):
    """The workload identity under which throughput is comparable: stage +
    bench config string + global batch + device kind + chips — and the
    conv-kernel implementation, so the regression scan never compares
    across ``--conv_impl`` arms (a pallas-arm number must not mask or
    fake an xla-path regression). The default 'xla' (and records predating
    the flag) key exactly as before, so the committed history's
    fingerprints stay stable (pure)."""
    ident = {
        "stage": stage,
        "config": detail.get("config"),
        "global_batch": detail.get("global_batch"),
        "device_kind": detail.get("device_kind"),
        "chips": detail.get("chips"),
    }
    conv_impl = detail.get("conv_impl", "xla")
    if conv_impl != "xla":
        ident["conv_impl"] = conv_impl
        # the pallas arm exists in fp32 AND bf16 compute (round 19): the
        # dtype changes the workload, so the scan must not compare across
        # it. Scoped to non-xla impls so every committed record (all xla
        # so far) keys exactly as before.
        ident["conv_dtype"] = detail.get("conv_dtype", "fp32")
    blob = json.dumps(ident, sort_keys=True).encode()
    return hashlib.sha1(blob).hexdigest()[:12]


def phase_shares_from_artifact(artifact):
    """``{phase: share}`` (steady_state included) from a trace_report
    artifact — the per-phase attribution that makes a ledger regression
    assignable to a phase, not just a revision."""
    rep = artifact.get("report", {})
    shares = {
        name: p.get("share") for name, p in rep.get("phases", {}).items()
    }
    steady = rep.get("steady_state", {})
    if "share" in steady:
        shares["steady_state"] = steady["share"]
    return shares


def record_from_bench(bench_record, git_revision, ts_unix,
                      phase_shares=None, note=""):
    """One schema-pinned ledger record from bench.py's headline JSON
    (pure; tests pin the shape)."""
    detail = bench_record.get("detail", {})
    metric = bench_record.get("metric", "")
    stage = metric.split("_imgs_per_sec")[0] or "unknown"
    rec = {
        "schema": SCHEMA,
        "ts_unix": round(float(ts_unix), 3),
        "git_rev": git_revision,
        "fingerprint": fingerprint_for(stage, detail),
        "stage": stage,
        "device_kind": detail.get("device_kind"),
        "chips": detail.get("chips"),
        "imgs_per_sec_per_chip": float(bench_record["value"]),
        "step_ms": detail.get("step_ms"),
        "clock_suspect": bool(detail.get("clock_suspect")),
        "vs_baseline": bench_record.get("vs_baseline"),
        "config": detail.get("config"),
    }
    if phase_shares:
        rec["phase_shares"] = phase_shares
    if note:
        rec["note"] = note
    return rec


CORRUPT_LINE_SCHEMA = "__corrupt_line__"


def load_ledger(path):
    """All ledger records. Tolerates ONLY a torn FINAL line (an append
    racing this reader, or a killed bench mid-write). A COMPLETE line
    that fails to parse becomes a sentinel record (schema
    :data:`CORRUPT_LINE_SCHEMA`) so :func:`schema_errors` flags it — the
    gate must refuse a history it cannot fully interpret, not silently
    judge the surviving records (a vanished newest record would make the
    previous one 'latest' and the scan blind to the regression)."""
    if not os.path.exists(path):
        return []
    with open(path) as f:
        text = f.read()
    consumed = text.rfind("\n") + 1
    records = []
    for i, line in enumerate(text[:consumed].splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            rec = None
        if not isinstance(rec, dict):
            rec = {"schema": CORRUPT_LINE_SCHEMA, "line": i + 1}
        records.append(rec)
    return records


def append_record(path, record):
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(record, sort_keys=True) + "\n")


def schema_errors(records):
    """Per-record schema violations (pure): the gate refuses a ledger it
    cannot interpret rather than skipping silently."""
    errors = []
    for i, rec in enumerate(records):
        if rec.get("schema") == CORRUPT_LINE_SCHEMA:
            errors.append(
                f"record {i}: unparseable ledger line {rec.get('line')}"
            )
            continue
        if rec.get("schema") != SCHEMA:
            errors.append(f"record {i}: schema {rec.get('schema')!r}")
            continue
        missing = [k for k in REQUIRED_KEYS if k not in rec]
        if missing:
            errors.append(f"record {i}: missing keys {missing}")
    return errors


def _phase_suspect(latest, trail):
    """The phase whose share grew most vs the trailing record that carries
    shares — the 'look here first' pointer next to a regression verdict."""
    ref = next(
        (r for r in reversed(trail) if r.get("phase_shares")), None
    )
    shares = latest.get("phase_shares")
    if not shares or ref is None:
        return None
    deltas = {
        name: shares[name] - ref["phase_shares"].get(name, 0.0)
        for name in shares
    }
    name, delta = max(deltas.items(), key=lambda kv: kv[1])
    if delta <= 0:
        return None
    return {"phase": name, "share_delta": round(delta, 4)}


def detect_regression(records, fraction=REGRESSION_FRACTION,
                      window=TRAIL_WINDOW, min_trail=MIN_TRAIL):
    """Per-fingerprint regression verdicts for the LATEST record of each
    group (pure). Clock-suspect runs are excluded both as the subject and
    as window members (the bench-gate convention). Returns
    ``{fingerprint: {"status": "ok"|"regression"|"skipped", ...}}``."""
    groups = {}
    for rec in records:
        groups.setdefault(rec["fingerprint"], []).append(rec)
    verdicts = {}
    for fp, group in groups.items():
        clean = [r for r in group if not r.get("clock_suspect")]
        label = {
            "stage": group[-1].get("stage"),
            "device_kind": group[-1].get("device_kind"),
            "chips": group[-1].get("chips"),
        }
        if not clean:
            verdicts[fp] = dict(
                label, status="skipped",
                reason="every run in the group is clock-suspect",
            )
            continue
        latest = clean[-1]
        trail = clean[:-1][-window:]
        if len(trail) < min_trail:
            verdicts[fp] = dict(
                label, status="skipped",
                value=latest["imgs_per_sec_per_chip"],
                reason=f"trailing clean window {len(trail)} < {min_trail}: "
                       "no history to regress against",
            )
            continue
        baseline = statistics.median(
            r["imgs_per_sec_per_chip"] for r in trail
        )
        value = latest["imgs_per_sec_per_chip"]
        ratio = value / baseline if baseline > 0 else 0.0
        verdict = dict(
            label,
            status="regression" if ratio < fraction else "ok",
            value=value,
            baseline_median=round(baseline, 1),
            ratio=round(ratio, 4),
            window=len(trail),
            latest_rev=latest.get("git_rev"),
            window_revs=[r.get("git_rev") for r in trail],
        )
        if verdict["status"] == "regression":
            suspect = _phase_suspect(latest, trail)
            if suspect:
                verdict["phase_suspect"] = suspect
        verdicts[fp] = verdict
    return verdicts


def build_check_output(ledger_path, records, verdicts):
    """The check artifact (pure; schema pinned by tests)."""
    return {
        "schema": CHECK_SCHEMA,
        "ledger": ledger_path,
        "n_records": len(records),
        "schema_errors": schema_errors(records),
        "verdicts": verdicts,
        "ok": bool(records) and not schema_errors(records) and not any(
            v["status"] == "regression" for v in verdicts.values()
        ),
    }


def parse_bench_json(path):
    """bench.py's headline record from a captured stdout/log file: the
    LAST parseable JSON line carrying a 'metric' key (warmup/progress
    noise above it is ignored), or None. THE one copy of the bench-stdout
    parsing contract — scripts/ratchet.py wraps this with its own error
    type."""
    record = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                record = obj
    return record


def _parse_bench_json(path):
    record = parse_bench_json(path)
    if record is None:
        raise SystemExit(f"no bench JSON record in {path}")
    return record


def append_from_bench(ledger_path, bench_record, phases_path="", note=""):
    """Build + append one record from a bench headline dict (what
    ``bench.py --ledger`` calls); returns the appended record."""
    shares = None
    if phases_path:
        with open(phases_path) as f:
            shares = phase_shares_from_artifact(json.load(f))
    rec = record_from_bench(
        bench_record, git_rev(), time.time(), phase_shares=shares, note=note
    )
    append_record(ledger_path, rec)
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_app = sub.add_parser("append", help="append one bench result")
    p_app.add_argument("--bench-json", required=True,
                       help="file holding bench.py's stdout (the last JSON "
                            "'metric' line is the record)")
    p_app.add_argument("--ledger", default=os.path.join(REPO, DEFAULT_LEDGER))
    p_app.add_argument("--phases", default="",
                       help="a trace_report artifact whose phase shares "
                            "ride the record")
    p_app.add_argument("--note", default="")
    p_chk = sub.add_parser("check", help="regression scan over the ledger")
    p_chk.add_argument("--ledger", default=os.path.join(REPO, DEFAULT_LEDGER))
    p_chk.add_argument("--json", default="",
                       help="write the check artifact here")
    args = ap.parse_args(argv)

    if args.cmd == "append":
        rec = append_from_bench(
            args.ledger, _parse_bench_json(args.bench_json),
            phases_path=args.phases, note=args.note,
        )
        print(json.dumps(rec, sort_keys=True))
        return 0

    records = load_ledger(args.ledger)
    # schema first: detect_regression indexes the pinned keys, so a
    # malformed record must surface as a schema error, not a KeyError
    verdicts = {} if schema_errors(records) else detect_regression(records)
    out = build_check_output(args.ledger, records, verdicts)
    for fp, v in sorted(verdicts.items()):
        print(json.dumps({"fingerprint": fp, **v}, sort_keys=True))
    for err in out["schema_errors"]:
        print(f"SCHEMA ERROR: {err}")
    print(json.dumps({
        "metric": "perf_ledger_check", "ok": out["ok"],
        "records": out["n_records"], "groups": len(verdicts),
    }))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {args.json}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
