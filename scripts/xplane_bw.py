#!/usr/bin/env python
"""Measured DRAM (HBM) bandwidth from a jax.profiler xplane capture.

Round-3 PERF.md's "0.97 of the HBM roofline" was an HLO-level UPPER BOUND:
``cost_analysis()`` byte counts include VMEM-resident fusion traffic. This
tool closes that gap from the device profiler's own per-op attribution:

- per-op **HBM-only** read/write bytes from the ``memory_access_breakdown``
  stat (memory_space = HBM entries only — on-chip VMEM/SRAM traffic is
  excluded), attached by the TPU profiler to every XLA op it timed;
- **measured** per-op and per-step durations from the trace timeline
  (the ``Steps`` line of the ``/device:TPU:0`` plane);
- the device's advertised peak HBM bandwidth from the same plane
  (``peak_hbm_bw_gigabytes_per_second`` — 819.2 GB/s on v5e).

DRAM utilization = (HBM bytes per step) / (measured step time x peak BW).
Also prints the top-N ops by HBM traffic with per-op achieved GB/s, so the
fattest fusions are attributable (VERDICT r3 #2).

Usage:
    python scripts/xplane_bw.py <trace_dir | path/to/*.xplane.pb> [--top 10]

(Parsing needs the image's tensorflow+xprof protos; run with
``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` if the binary protobuf
rejects the pregenerated modules.)
"""

import argparse
import collections
import glob
import json
import os
import sys


def _load_xspace(path):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: PLC0415

    if os.path.isdir(path):
        hits = sorted(
            glob.glob(os.path.join(path, "**", "*.xplane.pb"), recursive=True)
        )
        if not hits:
            sys.exit(f"no *.xplane.pb under {path}")
        path = hits[-1]
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs, path


def _stat_value(stat):
    which = stat.WhichOneof("value")
    return getattr(stat, which) if which else None


def _parse_breakdown(raw, memory_accessed_cls):
    """Wire-decode the repeated MemoryAccessed submessages of the
    ``memory_access_breakdown`` stat (the wrapper message type is not
    exported by the installed xprof protos; field 1 = LEN-delimited)."""
    out, i = [], 0
    while i < len(raw):
        tag = raw[i]
        i += 1
        if tag != 0x0A:
            return out  # unknown field past the repeated block: stop
        ln = shift = 0
        while True:
            b = raw[i]
            i += 1
            ln |= (b & 0x7F) << shift
            shift += 7
            if not b & 0x80:
                break
        ma = memory_accessed_cls()
        ma.ParseFromString(bytes(raw[i:i + ln]))
        i += ln
        out.append(ma)
    return out


def analyze(path, top_n=10, min_step_ms=1.0):
    from xprof.protobuf import op_metrics_pb2  # noqa: PLC0415

    OpMetrics = op_metrics_pb2.OpMetrics
    HBM = op_metrics_pb2.MemorySpace.Value("MEMORY_SPACE_HBM")

    xs, resolved = _load_xspace(path)
    tpu = next((p for p in xs.planes if "/device:TPU" in p.name), None)
    if tpu is None:
        sys.exit(f"no TPU plane in {resolved}")
    stat_names = {k: v.name for k, v in tpu.stat_metadata.items()}
    plane_stats = {
        stat_names[s.metadata_id]: _stat_value(s) for s in tpu.stats
    }
    peak_gbps = float(plane_stats.get("peak_hbm_bw_gigabytes_per_second", 0.0))

    # per-op-metadata: HLO-model bytes and profiler HBM read/write attribution
    info = {}
    for mid, md in tpu.event_metadata.items():
        stats = {stat_names[s.metadata_id]: _stat_value(s) for s in md.stats}
        hbm_bytes = 0
        raw = stats.get("memory_access_breakdown")
        if isinstance(raw, bytes) and raw:
            for ma in _parse_breakdown(raw, OpMetrics.MemoryAccessed):
                if ma.memory_space == HBM:
                    hbm_bytes += ma.bytes_accessed
        info[mid] = {
            "name": md.name,
            "category": stats.get("hlo_category", ""),
            "model_bytes": int(stats.get("bytes_accessed", 0) or 0),
            "hbm_bytes": int(hbm_bytes),
            "flops": int(stats.get("flops", 0) or 0),
        }

    peak_flops = float(plane_stats.get("peak_teraflops_per_second", 0.0)) * 1e12

    lines = {l.name: l for l in tpu.lines}
    for needed in ("Steps", "XLA Ops"):
        if needed not in lines:
            sys.exit(
                f"TPU plane has no '{needed}' line in {resolved} — capture "
                "the trace around real train steps (--trace_dir on a driver)"
            )
    # steady step windows: the Steps line's real train steps (>= min_step_ms),
    # first one dropped (warm-up / first-donation step)
    steps = [
        (e.offset_ps, e.offset_ps + e.duration_ps)
        for e in lines["Steps"].events
        if e.duration_ps >= min_step_ms * 1e9
    ]
    if len(steps) > 1:
        steps = steps[1:]
    if not steps:
        sys.exit("no step windows >= min_step_ms in the Steps line")

    def step_fraction(off, dur):
        """Fraction of [off, off+dur) inside the step windows: events
        straddling a window edge are clipped and their bytes/flops pro-rated
        instead of being wholly included (start-in-window) or wholly dropped
        (start-before-window) — removes the edge bias in hbm_gb_per_step."""
        if dur <= 0:
            return 1.0 if any(a <= off < b for a, b in steps) else 0.0
        end = off + dur
        overlap = sum(
            max(0, min(end, b) - max(off, a)) for a, b in steps
        )
        return overlap / dur

    per_op = collections.defaultdict(lambda: [0, 0.0, 0])  # bytes, ms, count
    per_cat = collections.defaultdict(lambda: [0, 0.0, 0])  # bytes, ms, flops
    total_hbm = 0
    total_model = 0
    busy_ps = 0
    mixed_floor_ps = 0.0  # sum over op executions of max(byte time, flop time)
    for ev in lines["XLA Ops"].events:
        frac = step_fraction(ev.offset_ps, ev.duration_ps)
        if frac <= 0.0:
            continue
        meta = info.get(ev.metadata_id)
        if meta is None:
            continue
        key = meta["name"]
        per_op[key][0] += meta["hbm_bytes"] * frac
        per_op[key][1] += ev.duration_ps * frac / 1e9
        per_op[key][2] += 1
        cat = meta["category"] or "uncategorized"
        per_cat[cat][0] += meta["hbm_bytes"] * frac
        per_cat[cat][1] += ev.duration_ps * frac / 1e9
        per_cat[cat][2] += meta["flops"] * frac
        total_hbm += meta["hbm_bytes"] * frac
        total_model += meta["model_bytes"] * frac
        busy_ps += ev.duration_ps * frac
        byte_time = meta["hbm_bytes"] / (peak_gbps * 1e9) if peak_gbps else 0
        flop_time = meta["flops"] / peak_flops if peak_flops else 0
        mixed_floor_ps += max(byte_time, flop_time) * frac * 1e12

    n_steps = len(steps)
    step_ms = sum(b - a for a, b in steps) / 1e9 / n_steps
    hbm_per_step = total_hbm / n_steps
    util = hbm_per_step / (step_ms / 1e3) / (peak_gbps * 1e9) if peak_gbps else 0
    busy_util = (
        total_hbm / (busy_ps / 1e12) / (peak_gbps * 1e9) if busy_ps else 0
    )

    rows = sorted(
        (
            {
                "op": k[:88],
                "category": "",
                "hbm_gb_per_step": v[0] / n_steps / 1e9,
                "ms_per_step": v[1] / n_steps,
                "achieved_gbps": (v[0] / 1e9) / (v[1] / 1e3) if v[1] else 0.0,
                "pct_of_step_traffic": 100.0 * v[0] / total_hbm,
            }
            for k, v in per_op.items()
        ),
        key=lambda r: -r["hbm_gb_per_step"],
    )[:top_n]

    categories = {
        cat: {
            "ms_per_step": round(v[1] / n_steps, 3),
            "hbm_gb_per_step": round(v[0] / n_steps / 1e9, 3),
            "achieved_gbps": round((v[0] / 1e9) / (v[1] / 1e3), 1) if v[1] else 0,
            "mfu": round(
                (v[2] / n_steps) / ((v[1] / n_steps / 1e3) * peak_flops), 3
            ) if v[1] and peak_flops else 0,
        }
        for cat, v in sorted(per_cat.items(), key=lambda x: -x[1][1])
    }
    mixed_floor_ms = mixed_floor_ps / 1e9 / n_steps
    result = {
        "xplane": resolved,
        "n_steps": n_steps,
        "measured_step_ms": round(step_ms, 3),
        "peak_hbm_gbps": round(peak_gbps, 1),
        "hbm_gb_per_step": round(hbm_per_step / 1e9, 3),
        "model_gb_per_step": round(total_model / n_steps / 1e9, 3),
        "measured_dram_utilization_of_step": round(util, 4),
        "dram_utilization_of_op_busy_time": round(busy_util, 4),
        # per-op-execution max(HBM-byte time, flop time), summed: the
        # roofline floor for THIS op mix with no fusion changes
        "mixed_roofline_floor_ms": round(mixed_floor_ms, 3),
        "fraction_of_mixed_roofline": round(mixed_floor_ms / step_ms, 4)
        if step_ms else 0,
        "categories": categories,
        "top_ops": rows,
    }
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace", help="trace dir or .xplane.pb path")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--markdown", action="store_true",
                    help="also print a markdown table of the top ops")
    args = ap.parse_args()
    result = analyze(args.trace, top_n=args.top)
    print(json.dumps(result))
    if args.markdown:
        print()
        print("| op | GB/step (HBM) | ms/step | achieved GB/s | % of traffic |")
        print("|---|---|---|---|---|")
        for r in result["top_ops"]:
            print(
                f"| `{r['op'][:60]}` | {r['hbm_gb_per_step']:.3f} "
                f"| {r['ms_per_step']:.3f} | {r['achieved_gbps']:.0f} "
                f"| {r['pct_of_step_traffic']:.1f} |"
            )


if __name__ == "__main__":
    main()
