#!/usr/bin/env python
"""Latency/throughput benchmark for the serve/ subsystem.

Two client models against one engine+batcher stack:

- **closed loop** — C client threads, each submitting back-to-back (a new
  request the moment the last completes). Measures the stack's saturated
  throughput and the latency it costs.
- **open loop** — Poisson arrivals at a fixed rate, submitted on schedule
  regardless of completions (the honest service-latency model: a closed loop
  self-throttles and hides queueing, an open loop exposes it).

Latencies are recorded per request and reported as p50/p95/p99 **per
bucket** (the engine pads request sizes up to jit buckets, so e.g. size-5
and size-7 requests share the bucket-8 program and the same latency
population). Results go to a JSON artifact (``--json``, default
``docs/evidence/serve_bench_smoke.json`` in smoke mode).

``--smoke`` is the CI end-to-end proof (tests/test_scripts.py): tiny
random-init model on CPU, a short closed + open loop through the REAL
DynamicBatcher, a duplicate-image pass through the REAL cache, and one
round trip through the REAL HTTP endpoint (/healthz, /embed, /stats on an
ephemeral port) — engine → batcher → cache → HTTP, nothing mocked.

Usage:
    python scripts/serve_bench.py --smoke
    python scripts/serve_bench.py --ckpt <run_dir>/last --duration 10 \
        --rate 200 --clients 8 --json docs/evidence/serve_bench.json
"""

import argparse
import base64
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.serve.batcher import (  # noqa: E402
    DynamicBatcher,
    QueueFull,
)
from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache  # noqa: E402
from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine  # noqa: E402
from simclr_pytorch_distributed_tpu.serve.server import (  # noqa: E402
    combined_stats_fn,
    create_server,
    start_in_thread,
)


def percentiles(latencies_ms):
    if not latencies_ms:
        return None
    arr = np.asarray(latencies_ms)
    return {
        "n": int(arr.size),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p95_ms": round(float(np.percentile(arr, 95)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "mean_ms": round(float(arr.mean()), 3),
        "max_ms": round(float(arr.max()), 3),
    }


def per_bucket_report(records, engine):
    """records: [(request_size, latency_ms)] -> {bucket: percentiles}."""
    by_bucket = {}
    for size, lat in records:
        by_bucket.setdefault(engine.bucket_for(size), []).append(lat)
    return {
        str(bucket): percentiles(lats)
        for bucket, lats in sorted(by_bucket.items())
    }


def make_images(rng, n, size):
    return rng.integers(0, 256, size=(n, size, size, 3), dtype=np.uint8)


def closed_loop(batcher, rng, *, clients, requests_per_client, sizes, size):
    """Each client thread submits back-to-back; returns (records, elapsed_s,
    total_images)."""
    records = []
    lock = threading.Lock()

    def client(seed):
        crng = np.random.default_rng(seed)
        for _ in range(requests_per_client):
            n = int(crng.choice(sizes))
            images = make_images(crng, n, size)
            t0 = time.perf_counter()
            batcher.submit(images).result(timeout=120)
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                records.append((n, dt))

    threads = [
        threading.Thread(target=client, args=(1000 + i,)) for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return records, elapsed, sum(n for n, _ in records)


def open_loop(batcher, rng, *, rate_rps, n_requests, sizes, size):
    """Poisson arrivals at ``rate_rps``; submission never waits on
    completions (futures resolve via callback)."""
    records = []
    lock = threading.Lock()
    pending = []
    shed = 0
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        n = int(rng.choice(sizes))
        images = make_images(rng, n, size)
        t0 = time.perf_counter()

        def on_done(fut, n=n, t0=t0):
            dt = (time.perf_counter() - t0) * 1e3
            if fut.exception() is None:
                with lock:
                    records.append((n, dt))

        try:
            fut = batcher.submit(images)
        except QueueFull:
            # open loop beyond capacity: backpressure sheds load instead of
            # growing the queue — count it, don't crash the arrival schedule
            shed += 1
            continue
        fut.add_done_callback(on_done)
        pending.append(fut)
    for fut in pending:
        fut.result(timeout=120)
    elapsed = time.perf_counter() - t_start
    return records, elapsed, sum(n for n, _ in records), shed


def http_round_trip(engine, batcher, size):
    """One real round trip through the stdlib HTTP endpoint on an ephemeral
    port: /healthz, /embed (both JSON encodings), /stats."""
    server = create_server(batcher, combined_stats_fn(engine, batcher), port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    out = {}
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            out["healthz"] = json.loads(r.read())["status"]
        images = make_images(np.random.default_rng(7), 2, size)
        body = json.dumps({
            "images_b64": base64.b64encode(images.tobytes()).decode(),
            "shape": list(images.shape),
        }).encode()
        req = urllib.request.Request(
            f"{base}/embed", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            reply = json.loads(r.read())
        out["embed_dim"] = reply["dim"]
        out["embed_n"] = reply["n"]
        # nested-list encoding of the same images must give the same answer
        body2 = json.dumps({"images": images.tolist()}).encode()
        req2 = urllib.request.Request(
            f"{base}/embed", data=body2,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2, timeout=60) as r:
            reply2 = json.loads(r.read())
        out["encodings_agree"] = bool(
            np.allclose(reply["embeddings"], reply2["embeddings"])
        )
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.loads(r.read())
        out["stats_keys"] = sorted(stats)
    finally:
        server.shutdown()
        server.server_close()
    return out


def cache_pass(batcher, engine, rng, size):
    """Submit the SAME images twice; the second pass must be answered from
    the cache (hits recorded, no new engine dispatches)."""
    images = make_images(rng, 4, size)
    batcher.submit(images).result(timeout=120)
    before = engine.stats()
    t0 = time.perf_counter()
    batcher.submit(images).result(timeout=120)
    warm_ms = (time.perf_counter() - t0) * 1e3
    after = engine.stats()
    return {
        "warm_latency_ms": round(warm_ms, 3),
        "hit_rows": after["cache_hit_rows"] - before["cache_hit_rows"],
        "extra_dispatches": (
            sum(after["bucket_dispatches"].values())
            - sum(before["bucket_dispatches"].values())
        ),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", default="",
                   help="checkpoint/run dir or .pth; empty = random init")
    p.add_argument("--model", default="resnet10")
    p.add_argument("--img_size", type=int, default=32)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch", type=int, default=128)
    p.add_argument("--max_wait_ms", type=float, default=5.0)
    p.add_argument("--max_queue", type=int, default=512)
    p.add_argument("--cache_capacity", type=int, default=4096)
    p.add_argument("--normalize", action="store_true")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests_per_client", type=int, default=25)
    p.add_argument("--rate", type=float, default=100.0,
                   help="open-loop Poisson arrival rate (requests/s)")
    p.add_argument("--open_requests", type=int, default=200)
    p.add_argument("--sizes", default="1,3,8,20",
                   help="request sizes drawn uniformly per request")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", dest="json_out", default=None)
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU end-to-end: engine→batcher→cache→HTTP")
    args = p.parse_args(argv)

    if args.smoke:
        # small enough that two bucket compiles + the loops fit a CI budget
        args.model = args.model if args.ckpt else "resnet10"
        args.img_size = min(args.img_size, 8)
        args.buckets = "2,8"
        args.max_batch = 8
        args.sizes = "1,2,5"
        args.clients = 3
        args.requests_per_client = 4
        args.rate = 200.0
        args.open_requests = 12
        if args.json_out is None:
            args.json_out = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "docs", "evidence", "serve_bench_smoke.json",
            )

    buckets = tuple(int(b) for b in args.buckets.split(","))
    sizes = tuple(int(s) for s in args.sizes.split(","))
    cache = EmbeddingCache(args.cache_capacity) if args.cache_capacity else None
    # the bench generates --img_size images, so pin the engine to match even
    # when a checkpoint's recorded training size differs
    kwargs = dict(buckets=buckets, normalize=args.normalize, cache=cache,
                  img_size=args.img_size)
    if args.ckpt:
        engine = EmbeddingEngine.from_checkpoint(args.ckpt, **kwargs)
    else:
        engine = EmbeddingEngine.random_init(
            model_name=args.model, size=args.img_size, seed=args.seed, **kwargs
        )
    batcher = DynamicBatcher(
        engine.embed, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        validate=engine.validate_images,
    )
    rng = np.random.default_rng(args.seed)

    # warm every bucket OUTSIDE the timed loops: compiles are a one-time cost
    # the steady-state latency distribution must not absorb
    for b in buckets:
        engine.embed(make_images(rng, b, args.img_size))

    closed_records, closed_s, closed_images = closed_loop(
        batcher, rng, clients=args.clients,
        requests_per_client=args.requests_per_client,
        sizes=sizes, size=args.img_size,
    )
    open_records, open_s, open_images, open_shed = open_loop(
        batcher, rng, rate_rps=args.rate, n_requests=args.open_requests,
        sizes=sizes, size=args.img_size,
    )
    cache_result = cache_pass(batcher, engine, rng, args.img_size) if cache else None
    http_result = http_round_trip(engine, batcher, args.img_size)
    batcher.close()

    out = {
        "metric": "serve_bench",
        "mode": "smoke" if args.smoke else "full",
        "model": engine.model.model_name,
        "img_size": args.img_size,
        "buckets": list(buckets),
        "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms,
        "request_sizes": list(sizes),
        "closed_loop": {
            "clients": args.clients,
            "requests": len(closed_records),
            "throughput_rps": round(len(closed_records) / closed_s, 2),
            "throughput_imgs_per_s": round(closed_images / closed_s, 2),
            "latency_by_bucket": per_bucket_report(closed_records, engine),
        },
        "open_loop": {
            "target_rate_rps": args.rate,
            "requests": len(open_records),
            "shed_by_backpressure": open_shed,
            "achieved_rate_rps": round(len(open_records) / open_s, 2),
            "throughput_imgs_per_s": round(open_images / open_s, 2),
            "latency_by_bucket": per_bucket_report(open_records, engine),
        },
        "cache": cache_result,
        "http": http_result,
        "engine_stats": engine.stats(),
        "batcher_stats": batcher.stats(),
        "device": str(engine.mesh.devices.flat[0].device_kind),
    }
    print(json.dumps(out, indent=1))
    if args.json_out:
        os.makedirs(os.path.dirname(os.path.abspath(args.json_out)), exist_ok=True)
        with open(args.json_out, "w") as f:
            json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    main()
