#!/usr/bin/env python
"""Latency/throughput benchmark for the serve/ subsystem.

Two client models against one engine+batcher stack:

- **closed loop** — C client threads, each submitting back-to-back (a new
  request the moment the last completes). Measures the stack's saturated
  throughput and the latency it costs.
- **open loop** — Poisson arrivals at a fixed rate, submitted on schedule
  regardless of completions (the honest service-latency model: a closed loop
  self-throttles and hides queueing, an open loop exposes it).

``--sweep`` is the saturation mode: the offered open-loop rate climbs a
geometric ladder until throughput plateaus, p99 blows up, or backpressure
sheds most arrivals — run once against the legacy synchronous path
(``max_inflight=1``, ``embed``) and once against the pipelined path
(``dispatch``/completion split, ``--max_inflight`` batches in flight), so
the committed artifact (``docs/evidence/serve_bench_sweep.json``) is a
before/after saturated-throughput comparison with per-window latency and
pipeline-occupancy gauges.

Latencies are recorded per request and reported as p50/p95/p99 **per
bucket** (the engine pads request sizes up to jit buckets, so e.g. size-5
and size-7 requests share the bucket-8 program and the same latency
population). Results go to a JSON artifact (``--json``, default
``docs/evidence/serve_bench_smoke.json`` in smoke mode).

``--sweep`` additionally runs a **mixed-tenant multi-model arm**: two
checkpoint versions hosted behind one ``ModelRegistry`` (serve/fleet/),
driven by a skewed tenant mix (a bulk tenant hammering the default model,
an interactive tenant on the canary) — per-model throughput/latency plus
the admission-controller counters land in the artifact under
``multi_model`` — and a **retrieval arm**: closed-loop ``/neighbors``
under mixed ``/embed`` load, run once per ``--retrieval_impl`` rung
(brute :class:`NeighborIndex` vs :class:`IVFIndex`) on the same workload
stream, per-impl query latency and index counters under ``retrieval``.

``--smoke`` is the CI end-to-end proof (tests/test_scripts.py): tiny
random-init model on CPU, a short closed + open loop through the REAL
DynamicBatcher, a duplicate-image pass through the REAL cache, and one
round trip through the REAL HTTP endpoint (/healthz, /embed, /stats on an
ephemeral port) — engine → batcher → cache → HTTP, nothing mocked.

Usage:
    python scripts/serve_bench.py --smoke
    python scripts/serve_bench.py --ckpt <run_dir>/last --duration 10 \
        --rate 200 --clients 8 --json docs/evidence/serve_bench.json
"""

import argparse
import base64
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from simclr_pytorch_distributed_tpu.serve.batcher import (  # noqa: E402
    DynamicBatcher,
    QueueFull,
)
from simclr_pytorch_distributed_tpu.serve.cache import EmbeddingCache  # noqa: E402
from simclr_pytorch_distributed_tpu.serve.engine import EmbeddingEngine  # noqa: E402
from simclr_pytorch_distributed_tpu.serve.server import (  # noqa: E402
    combined_stats_fn,
    create_server,
    start_in_thread,
)


def percentiles(latencies_ms):
    if not latencies_ms:
        return None
    arr = np.asarray(latencies_ms)
    return {
        "n": int(arr.size),
        "p50_ms": round(float(np.percentile(arr, 50)), 3),
        "p95_ms": round(float(np.percentile(arr, 95)), 3),
        "p99_ms": round(float(np.percentile(arr, 99)), 3),
        "mean_ms": round(float(arr.mean()), 3),
        "max_ms": round(float(arr.max()), 3),
    }


def per_bucket_report(records, engine):
    """records: [(request_size, latency_ms)] -> {bucket: percentiles}."""
    by_bucket = {}
    for size, lat in records:
        by_bucket.setdefault(engine.bucket_for(size), []).append(lat)
    return {
        str(bucket): percentiles(lats)
        for bucket, lats in sorted(by_bucket.items())
    }


def make_images(rng, n, size):
    return rng.integers(0, 256, size=(n, size, size, 3), dtype=np.uint8)


def emit_artifact(out, json_out):
    print(json.dumps(out, indent=1))
    if json_out:
        os.makedirs(os.path.dirname(os.path.abspath(json_out)), exist_ok=True)
        with open(json_out, "w") as f:
            json.dump(out, f, indent=1)
    return out


def closed_loop(batcher, rng, *, clients, requests_per_client, sizes, size):
    """Each client thread submits back-to-back; returns (records, elapsed_s,
    total_images)."""
    records = []
    lock = threading.Lock()

    def client(seed):
        crng = np.random.default_rng(seed)
        for _ in range(requests_per_client):
            n = int(crng.choice(sizes))
            images = make_images(crng, n, size)
            t0 = time.perf_counter()
            batcher.submit(images).result(timeout=120)
            dt = (time.perf_counter() - t0) * 1e3
            with lock:
                records.append((n, dt))

    threads = [
        threading.Thread(target=client, args=(1000 + i,)) for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    return records, elapsed, sum(n for n, _ in records)


def open_loop(batcher, rng, *, rate_rps, n_requests, sizes, size):
    """Poisson arrivals at ``rate_rps``; submission never waits on
    completions (futures resolve via callback)."""
    records = []
    lock = threading.Lock()
    pending = []
    shed = 0
    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    t_start = time.perf_counter()
    t_next = t_start
    for i in range(n_requests):
        t_next += gaps[i]
        delay = t_next - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        n = int(rng.choice(sizes))
        images = make_images(rng, n, size)
        t0 = time.perf_counter()

        def on_done(fut, n=n, t0=t0):
            dt = (time.perf_counter() - t0) * 1e3
            if fut.exception() is None:
                with lock:
                    records.append((n, dt))

        try:
            fut = batcher.submit(images)
        except QueueFull:
            # open loop beyond capacity: backpressure sheds load instead of
            # growing the queue — count it, don't crash the arrival schedule
            shed += 1
            continue
        fut.add_done_callback(on_done)
        pending.append(fut)
    for fut in pending:
        fut.result(timeout=120)
    elapsed = time.perf_counter() - t_start
    return records, elapsed, sum(n for n, _ in records), shed


def http_round_trip(engine, batcher, size):
    """One real round trip through the stdlib HTTP endpoint on an ephemeral
    port: /healthz, /embed (both JSON encodings), /stats."""
    server = create_server(batcher, combined_stats_fn(engine, batcher), port=0)
    start_in_thread(server)
    host, port = server.server_address[:2]
    base = f"http://{host}:{port}"
    out = {}
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as r:
            out["healthz"] = json.loads(r.read())["status"]
        images = make_images(np.random.default_rng(7), 2, size)
        body = json.dumps({
            "images_b64": base64.b64encode(images.tobytes()).decode(),
            "shape": list(images.shape),
        }).encode()
        req = urllib.request.Request(
            f"{base}/embed", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            reply = json.loads(r.read())
        out["embed_dim"] = reply["dim"]
        out["embed_n"] = reply["n"]
        # nested-list encoding of the same images must give the same answer
        body2 = json.dumps({"images": images.tolist()}).encode()
        req2 = urllib.request.Request(
            f"{base}/embed", data=body2,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req2, timeout=60) as r:
            reply2 = json.loads(r.read())
        out["encodings_agree"] = bool(
            np.allclose(reply["embeddings"], reply2["embeddings"])
        )
        with urllib.request.urlopen(f"{base}/stats", timeout=10) as r:
            stats = json.loads(r.read())
        out["stats_keys"] = sorted(stats)
    finally:
        server.shutdown()
        server.server_close()
    return out


def make_batcher(engine, args, *, pipelined):
    """The two comparison arms: ``pipelined=False`` is the pre-pipeline
    synchronous path (dispatch+complete serialized per batch), ``True`` is
    the split-stage path with ``--max_inflight`` batches on device."""
    kwargs = dict(
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue, validate=engine.validate_images,
    )
    if pipelined:
        return DynamicBatcher(
            dispatch_fn=engine.dispatch, max_inflight=args.max_inflight,
            max_inflight_images=args.max_inflight_images, **kwargs,
        )
    return DynamicBatcher(engine.embed, max_inflight=1, **kwargs)


INFLIGHT_GAUGES = (
    "dispatched_batches", "batches", "max_inflight_observed",
    "pipeline_occupancy", "avg_inflight_depth",
)


def sweep_window(engine, args, rng, rate, *, pipelined, sizes):
    """One offered-rate window on a FRESH batcher (per-window gauges start
    clean; the engine and its compiled programs are shared across windows)."""
    batcher = make_batcher(engine, args, pipelined=pipelined)
    try:
        records, elapsed, images, shed = open_loop(
            batcher, rng, rate_rps=rate, n_requests=args.sweep_requests,
            sizes=sizes, size=args.img_size,
        )
    finally:
        batcher.close()
    bstats = batcher.stats()
    return {
        "offered_rate_rps": rate,
        "requests_completed": len(records),
        "shed_by_backpressure": shed,
        "achieved_rate_rps": round(len(records) / elapsed, 2),
        "throughput_imgs_per_s": round(images / elapsed, 2),
        "latency": percentiles([lat for _, lat in records]),
        "inflight": {
            k: round(bstats[k], 4) if isinstance(bstats[k], float) else bstats[k]
            for k in INFLIGHT_GAUGES
        },
    }


def _arm_stop_reason(windows, args):
    """Saturation test for one arm's window history (latest = windows[-1])."""
    w = windows[-1]
    offered = w["requests_completed"] + w["shed_by_backpressure"]
    if offered and w["shed_by_backpressure"] / offered > 0.5:
        return "backpressure_shed"
    if w["latency"] and windows[0]["latency"] and (
        w["latency"]["p99_ms"]
        > args.sweep_p99_blowup * windows[0]["latency"]["p99_ms"]
    ):
        return "p99_blowup"
    if len(windows) >= 3:
        best_before = max(x["throughput_imgs_per_s"] for x in windows[:-1])
        if w["throughput_imgs_per_s"] < (
            (1.0 + args.sweep_plateau_frac) * best_before
        ):
            return "throughput_plateau"
    return None


def _arm_summary(arm, args):
    windows = arm["windows"]
    low = windows[0]["latency"] or {}
    return {
        "max_inflight": args.max_inflight if arm["pipelined"] else 1,
        "stop_reason": arm["stop"] or "max_windows",
        "windows": windows,
        "saturated_imgs_per_s": max(
            w["throughput_imgs_per_s"] for w in windows
        ),
        "low_load_p50_ms": low.get("p50_ms"),
        "low_load_p99_ms": low.get("p99_ms"),
    }


def paired_saturation_sweep(engine, args):
    """Climb the offered-rate ladder on BOTH arms until each saturates.

    The comparison is paired twice over: rung k of both arms draws from
    ``default_rng(seed + k)`` (identical request-size mixes and arrival
    schedules — with sizes spanning 1..20 and a few dozen requests per
    window, an unpaired draw moves p50 far more than the treatment does),
    and the two arms run back-to-back WITHIN each rung, alternating which
    goes first (ABBA), so machine-load drift across the sweep lands on
    both arms rather than on whichever ran second. An arm that hits its
    stop condition drops out; the ladder ends when both have."""
    sizes = tuple(int(s) for s in args.sizes.split(","))
    arms = {
        "baseline": {"pipelined": False, "windows": [], "stop": None},
        "pipelined": {"pipelined": True, "windows": [], "stop": None},
    }
    # one discarded warm window per arm: the first-ever open loop pays
    # one-time costs (thread spin-up, allocator warm) that would otherwise
    # land entirely on whichever arm runs first and skew the rung-0 pair
    for name in ("baseline", "pipelined"):
        warm_args = argparse.Namespace(**vars(args))
        warm_args.sweep_requests = min(20, args.sweep_requests)
        sweep_window(
            engine, warm_args, np.random.default_rng(args.seed + 999_983),
            args.sweep_start_rate, pipelined=arms[name]["pipelined"],
            sizes=sizes,
        )
    rate = args.sweep_start_rate
    for k in range(args.sweep_max_windows):
        order = (
            ("baseline", "pipelined") if k % 2 == 0
            else ("pipelined", "baseline")
        )
        for name in order:
            arm = arms[name]
            if arm["stop"]:
                continue
            rng = np.random.default_rng(args.seed + k)
            arm["windows"].append(sweep_window(
                engine, args, rng, rate, pipelined=arm["pipelined"],
                sizes=sizes,
            ))
            arm["stop"] = _arm_stop_reason(arm["windows"], args)
        if all(a["stop"] for a in arms.values()):
            break
        rate *= args.sweep_factor
    return {name: _arm_summary(arm, args) for name, arm in arms.items()}


def multi_model_arm(args, rng, sizes):
    """Mixed-tenant multi-model arm: two versions of the model hosted
    behind one ModelRegistry, a skewed tenant mix (bulk tenant -> default
    model ~3:1 over interactive tenant -> canary), every request routed
    through registry.submit's admission + per-model batchers. Reports
    per-model latency/throughput and the admission counters — the fleet
    analogue of the single-model arms.

    The arm is CLOSED-LOOP with one request outstanding across the whole
    registry: on a multi-device mesh, two engines' compiled programs run
    concurrently under the pipelined path, and XLA's collective rendezvous
    deadlocks when different executables' collectives (the CPU backend
    consolidates sharded outputs through a compiled AllGather) interleave
    across device threads — the cross-MODEL analogue of the training-side
    collective-schedule contract. One program in flight at a time is the
    safe schedule; production hosts one model per replica (the
    serve_fleet_scenario geometry), where the hazard does not arise."""
    from simclr_pytorch_distributed_tpu.serve.fleet import (
        AdmissionController,
        ModelRegistry,
    )

    buckets = tuple(int(b) for b in args.buckets.split(","))
    engine_kwargs = dict(
        buckets=buckets, img_size=args.img_size, dtype=args.dtype
    )
    registry = ModelRegistry(
        batcher_kwargs=dict(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            max_queue=args.max_queue, max_inflight=args.max_inflight,
            max_inflight_images=args.max_inflight_images,
        ),
        admission=AdmissionController(max_tenant_rows=0),
        index_capacity=0,
    )
    try:
        for name, seed in (("prod", args.seed), ("canary", args.seed + 1)):
            engine = EmbeddingEngine.random_init(
                model_name=args.model, size=args.img_size, seed=seed,
                **engine_kwargs,
            )
            # warm outside the timed loop, like the single-model arms
            for b in buckets:
                engine.embed(make_images(rng, b, args.img_size))
            registry.add_model(name, engine)

        plan = []
        for _ in range(args.sweep_requests):
            if rng.random() < 0.75:
                plan.append(("prod", "bulk"))
            else:
                plan.append(("canary", "interactive"))
        records = {"prod": [], "canary": []}
        images_by_model = {"prod": 0, "canary": 0}
        shed = 0
        t_start = time.perf_counter()
        done = 0
        for model, tenant in plan:
            n = int(rng.choice(sizes))
            images = make_images(rng, n, args.img_size)
            t0 = time.perf_counter()
            try:
                name, fut = registry.submit(images, model=model, tenant=tenant)
            except QueueFull:
                shed += 1
                continue
            # closed-loop: wait before the next submit so at most one
            # compiled program is ever in flight across the two engines
            # (see the docstring's collective-schedule note)
            fut.result(timeout=120)
            records[name].append((time.perf_counter() - t0) * 1e3)
            images_by_model[name] += n
            done += 1
        elapsed = time.perf_counter() - t_start
        stats = registry.stats()
        return {
            "tenancy": {"bulk": "prod", "interactive": "canary"},
            "requests": done,
            "shed_by_backpressure": shed,
            "elapsed_s": round(elapsed, 3),
            "throughput_imgs_per_s": round(
                sum(images_by_model.values()) / elapsed, 2
            ),
            "per_model": {
                name: {
                    "requests": len(lat),
                    "images": images_by_model[name],
                    "latency": percentiles(lat),
                    "errors": stats["models"][name]["batcher"]["errors"],
                }
                for name, lat in records.items()
            },
            "admission": stats["admission"],
        }
    finally:
        registry.close()


def retrieval_arm(args, rng, sizes):
    """Closed-loop /neighbors under mixed /embed load, once per retrieval
    impl: a single-model registry whose index is the brute
    :class:`NeighborIndex` on one arm and :class:`IVFIndex` on the other,
    driven by the SAME workload stream (same arm seed -> identical images,
    sizes, and query schedule). Every request embeds through the real
    batcher and feeds the index (the /embed server path); every second
    request then doubles as a /neighbors client, timing only the
    ``neighbors_lookup`` — the number the impl ladder actually changes.
    Reports per-impl embed/query latency plus the index counters, and the
    brute/ivf query-p50 ratio the sweep artifact pins.

    Closed-loop for the same reason as :func:`multi_model_arm`: one
    compiled program in flight at a time keeps the CPU backend's
    collective rendezvous off the table."""
    from simclr_pytorch_distributed_tpu.serve.fleet import (
        AdmissionController,
        ModelRegistry,
    )
    from simclr_pytorch_distributed_tpu.serve.fleet import ivf as ivf_mod

    buckets = tuple(int(b) for b in args.buckets.split(","))
    capacity = 4096
    # small lists + a low train floor so the IVF arm reaches the TRAINED
    # path even at smoke row counts (~dozens of rows), not just the
    # provisional single-list rung
    nlist, nprobe, train_min_rows = 8, 4, 32
    arms = {}
    for impl in ("brute", "ivf"):
        factory = None
        if impl == "ivf":
            factory = lambda dim: ivf_mod.IVFIndex(  # noqa: E731
                dim, capacity=capacity, nlist=nlist, nprobe=nprobe,
                seed=args.seed, train_min_rows=train_min_rows,
            )
        registry = ModelRegistry(
            batcher_kwargs=dict(
                max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
                max_queue=args.max_queue, max_inflight=args.max_inflight,
                max_inflight_images=args.max_inflight_images,
            ),
            admission=AdmissionController(max_tenant_rows=0),
            index_capacity=capacity,
            index_factory=factory,
        )
        try:
            engine = EmbeddingEngine.random_init(
                model_name=args.model, size=args.img_size, seed=args.seed,
                buckets=buckets, img_size=args.img_size, dtype=args.dtype,
            )
            for b in buckets:
                engine.embed(make_images(rng, b, args.img_size))
            registry.add_model("prod", engine)

            # one rng per arm, same seed: both impls see the same workload
            arm_rng = np.random.default_rng(args.seed + 17)
            embed_lat, query_lat = [], []
            for i in range(args.sweep_requests):
                n = int(arm_rng.choice(sizes))
                images = make_images(arm_rng, n, args.img_size)
                t0 = time.perf_counter()
                name, fut = registry.submit(
                    images, model="prod", tenant="bench"
                )
                emb = fut.result(timeout=120)
                embed_lat.append((time.perf_counter() - t0) * 1e3)
                registry.index_add(name, images, emb)
                if i % 2 == 1:
                    t0 = time.perf_counter()
                    registry.neighbors_lookup(name, emb[:1], 5)
                    query_lat.append((time.perf_counter() - t0) * 1e3)
            index_stats = registry.stats()["models"]["prod"]["index"]
            arms[impl] = {
                "requests": args.sweep_requests,
                "neighbors_queries": len(query_lat),
                "embed_latency": percentiles(embed_lat),
                "query_latency": percentiles(query_lat),
                "index": index_stats,
            }
        finally:
            registry.close()
    brute_p50 = (arms["brute"]["query_latency"] or {}).get("p50_ms")
    ivf_p50 = (arms["ivf"]["query_latency"] or {}).get("p50_ms")
    return {
        "capacity": capacity,
        "nlist": nlist,
        "nprobe": nprobe,
        "k": 5,
        "per_impl": arms,
        "query_p50_ratio_brute_over_ivf": (
            round(brute_p50 / ivf_p50, 3) if brute_p50 and ivf_p50 else None
        ),
    }


def cache_pass(batcher, engine, rng, size):
    """Submit the SAME images twice; the second pass must be answered from
    the cache (hits recorded, no new engine dispatches)."""
    images = make_images(rng, 4, size)
    batcher.submit(images).result(timeout=120)
    before = engine.stats()
    t0 = time.perf_counter()
    batcher.submit(images).result(timeout=120)
    warm_ms = (time.perf_counter() - t0) * 1e3
    after = engine.stats()
    return {
        "warm_latency_ms": round(warm_ms, 3),
        "hit_rows": after["cache_hit_rows"] - before["cache_hit_rows"],
        "extra_dispatches": (
            sum(after["bucket_dispatches"].values())
            - sum(before["bucket_dispatches"].values())
        ),
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", default="",
                   help="checkpoint/run dir or .pth; empty = random init")
    p.add_argument("--model", default="resnet10")
    p.add_argument("--img_size", type=int, default=32)
    p.add_argument("--buckets", default="1,8,32,128")
    p.add_argument("--max_batch", type=int, default=128)
    p.add_argument("--max_wait_ms", type=float, default=5.0)
    p.add_argument("--max_queue", type=int, default=512)
    p.add_argument("--cache_capacity", type=int, default=4096)
    p.add_argument("--normalize", action="store_true")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--requests_per_client", type=int, default=25)
    p.add_argument("--rate", type=float, default=100.0,
                   help="open-loop Poisson arrival rate (requests/s)")
    p.add_argument("--open_requests", type=int, default=200)
    p.add_argument("--sizes", default="1,3,8,20",
                   help="request sizes drawn uniformly per request")
    p.add_argument("--max_inflight", type=int, default=3,
                   help="pipeline window for the pipelined arm")
    p.add_argument("--max_inflight_images", type=int, default=4096)
    p.add_argument("--dtype", default="fp32", choices=["fp32", "bf16"],
                   help="serving compute dtype (bf16: params+activations)")
    p.add_argument("--sweep", action="store_true",
                   help="saturation sweep: climb offered open-loop rate on "
                        "the synchronous AND pipelined paths until each "
                        "saturates; emits the before/after artifact")
    p.add_argument("--sweep_start_rate", type=float, default=40.0)
    p.add_argument("--sweep_factor", type=float, default=1.7,
                   help="offered-rate multiplier per window")
    p.add_argument("--sweep_max_windows", type=int, default=8)
    p.add_argument("--sweep_requests", type=int, default=150,
                   help="open-loop requests per window")
    p.add_argument("--sweep_plateau_frac", type=float, default=0.08,
                   help="stop when a window beats the best-so-far by less "
                        "than this fraction")
    p.add_argument("--sweep_p99_blowup", type=float, default=15.0,
                   help="stop when p99 exceeds this multiple of the "
                        "first window's p99")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--json", dest="json_out", default=None)
    p.add_argument("--smoke", action="store_true",
                   help="tiny CPU end-to-end: engine→batcher→cache→HTTP")
    args = p.parse_args(argv)

    if args.smoke:
        # small enough that two bucket compiles + the loops fit a CI budget
        args.model = args.model if args.ckpt else "resnet10"
        args.img_size = min(args.img_size, 8)
        args.buckets = "2,8"
        args.max_batch = 8
        args.sizes = "1,2,5"
        args.clients = 3
        args.requests_per_client = 4
        args.rate = 200.0
        args.open_requests = 12
        args.sweep_start_rate = 150.0
        args.sweep_factor = 2.0
        args.sweep_max_windows = 3
        args.sweep_requests = 24
        if args.json_out is None:
            args.json_out = os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "docs", "evidence",
                "serve_bench_sweep_smoke.json" if args.sweep
                else "serve_bench_smoke.json",
            )
    elif args.sweep and args.json_out is None:
        # the full sweep IS the evidence run — always leave the artifact
        args.json_out = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "docs", "evidence", "serve_bench_sweep.json",
        )

    buckets = tuple(int(b) for b in args.buckets.split(","))
    sizes = tuple(int(s) for s in args.sizes.split(","))
    # the sweep measures the COMPUTE path: a content cache would turn repeat
    # randomness into hits and flatter the throughput curve
    cache = (
        EmbeddingCache(args.cache_capacity)
        if args.cache_capacity and not args.sweep else None
    )
    # the bench generates --img_size images, so pin the engine to match even
    # when a checkpoint's recorded training size differs
    kwargs = dict(buckets=buckets, normalize=args.normalize, cache=cache,
                  img_size=args.img_size, dtype=args.dtype)
    if args.ckpt:
        engine = EmbeddingEngine.from_checkpoint(args.ckpt, **kwargs)
    else:
        engine = EmbeddingEngine.random_init(
            model_name=args.model, size=args.img_size, seed=args.seed, **kwargs
        )
    rng = np.random.default_rng(args.seed)

    # warm every bucket OUTSIDE the timed loops: compiles are a one-time cost
    # the steady-state latency distribution must not absorb
    for b in buckets:
        engine.embed(make_images(rng, b, args.img_size))

    if args.sweep:
        sweeps = paired_saturation_sweep(engine, args)
        baseline, pipelined = sweeps["baseline"], sweeps["pipelined"]
        # end-to-end proof through the PIPELINED stack: assembler -> inflight
        # window -> completer -> HTTP
        http_batcher = make_batcher(engine, args, pipelined=True)
        try:
            http_result = http_round_trip(engine, http_batcher, args.img_size)
        finally:
            http_batcher.close()
        out = {
            "metric": "serve_bench_sweep",
            "mode": "smoke" if args.smoke else "full",
            "model": engine.model.model_name,
            "dtype": args.dtype,
            "img_size": args.img_size,
            "buckets": list(buckets),
            "max_batch": args.max_batch,
            "max_wait_ms": args.max_wait_ms,
            "request_sizes": list(sizes),
            "sweep_requests_per_window": args.sweep_requests,
            "baseline": baseline,
            "pipelined": pipelined,
            "saturated_speedup": round(
                pipelined["saturated_imgs_per_s"]
                / max(baseline["saturated_imgs_per_s"], 1e-9), 3
            ),
            "low_load_p50_ratio": (
                round(pipelined["low_load_p50_ms"] / baseline["low_load_p50_ms"], 3)
                if pipelined["low_load_p50_ms"] and baseline["low_load_p50_ms"]
                else None
            ),
            "http": http_result,
            "multi_model": multi_model_arm(args, rng, sizes),
            "retrieval": retrieval_arm(args, rng, sizes),
            "engine_stats": engine.stats(),
            "device": str(engine.mesh.devices.flat[0].device_kind),
        }
        return emit_artifact(out, args.json_out)

    batcher = DynamicBatcher(
        dispatch_fn=engine.dispatch, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        max_inflight=args.max_inflight,
        max_inflight_images=args.max_inflight_images,
        validate=engine.validate_images,
    )

    closed_records, closed_s, closed_images = closed_loop(
        batcher, rng, clients=args.clients,
        requests_per_client=args.requests_per_client,
        sizes=sizes, size=args.img_size,
    )
    open_records, open_s, open_images, open_shed = open_loop(
        batcher, rng, rate_rps=args.rate, n_requests=args.open_requests,
        sizes=sizes, size=args.img_size,
    )
    cache_result = cache_pass(batcher, engine, rng, args.img_size) if cache else None
    http_result = http_round_trip(engine, batcher, args.img_size)
    batcher.close()

    out = {
        "metric": "serve_bench",
        "mode": "smoke" if args.smoke else "full",
        "model": engine.model.model_name,
        "dtype": args.dtype,
        "img_size": args.img_size,
        "buckets": list(buckets),
        "max_batch": args.max_batch,
        "max_wait_ms": args.max_wait_ms,
        "request_sizes": list(sizes),
        "closed_loop": {
            "clients": args.clients,
            "requests": len(closed_records),
            "throughput_rps": round(len(closed_records) / closed_s, 2),
            "throughput_imgs_per_s": round(closed_images / closed_s, 2),
            "latency_by_bucket": per_bucket_report(closed_records, engine),
        },
        "open_loop": {
            "target_rate_rps": args.rate,
            "requests": len(open_records),
            "shed_by_backpressure": open_shed,
            "achieved_rate_rps": round(len(open_records) / open_s, 2),
            "throughput_imgs_per_s": round(open_images / open_s, 2),
            "latency_by_bucket": per_bucket_report(open_records, engine),
        },
        "cache": cache_result,
        "http": http_result,
        "engine_stats": engine.stats(),
        "batcher_stats": batcher.stats(),
        "device": str(engine.mesh.devices.flat[0].device_kind),
    }
    return emit_artifact(out, args.json_out)


if __name__ == "__main__":
    main()
