"""Shared honest-sync timing harness for the scripts/ benchmarks.

Seconds per iteration of ``core``, dispatch amortized: ``iters`` iterations
run INSIDE one jitted ``fori_loop`` (each chained on the previous scalar, so
the loop cannot be parallelized or hoisted), one dispatch + one
computed-scalar readback per window. A separate 1-iteration program measures
the dispatch+readback floor, subtracted from the per-iter quotient. On this
tunneled chip the floor is ~2 ms — larger than the kernels being measured —
which is why a python-loop-of-dispatches cannot resolve these shapes (see
docs/PERF.md "Measurement methodology").

``core(i, lead, *rest)`` receives the loop index ``i`` (for per-iteration
randomness via ``fold_in``; ignore it for fixed inputs) and ``lead`` =
``args[0]`` perturbed by the carried scalar — the data-dependence that chains
each iteration on the previous one. It must return a scalar that depends on
the iteration's computation (so nothing is dead-code-eliminated).
"""

import statistics
import time

import jax
import jax.numpy as jnp
import numpy as np


def time_per_iter(core, args, iters=100, windows=5):
    """Median seconds per iteration of ``core`` over ``windows`` windows."""
    if iters < 2:
        # the dispatch floor is subtracted via the (iters - 1) quotient below:
        # iters=1 would divide by zero AFTER the warmup compiles, and iters<1
        # would silently mismeasure — fail loudly before any work instead
        # (callers pass CLI --iters values straight through)
        raise ValueError(f"iters must be >= 2 to subtract the dispatch floor, got {iters}")

    def make(n_iters):
        @jax.jit
        def run(tick, *a):
            def body(i, t):
                lead = a[0] + t * 1e-20  # data-dependence on the prior iter
                return t + core(i, lead, *a[1:])
            return jax.lax.fori_loop(0, n_iters, body, tick)
        return run

    looped, single = make(iters), make(1)
    tick = jnp.float32(0.0)
    float(looped(tick, *args))  # compile+warm
    float(single(tick, *args))

    def window_times(fn):
        dts = []
        for _ in range(windows):
            t = jnp.float32(0.0)
            t0 = time.perf_counter()
            out = float(fn(t, *args))  # computed-scalar readback: the only real sync
            dts.append(time.perf_counter() - t0)
            assert np.isfinite(out)
        return statistics.median(dts)

    floor = window_times(single)           # dispatch + readback + 1 iter
    total = window_times(looped)           # dispatch + readback + N iters
    return max(total - floor, 0.0) / (iters - 1)
