#!/usr/bin/env python
"""One supervisable command that owns a REAL multi-process gloo fleet —
the straggler scenario's victim (scripts/supervisor_matrix.py).

The supervisor babysits exactly ONE child process, but the straggler story
is inherently multi-process: the skew signal comes from the widened
failure-code allgather (utils/telemetry.py), which only exists when real
processes rendezvous over gloo. This launcher is the bridge — the
single-host stand-in for the scheduler-level fleet launcher a real pod has:

- spawns ``--nproc`` ``tests/multiprocess_child.py`` driver-mode workers
  (full pretrain: epoch loops, collective saves, preempt machinery) on a
  freshly picked coordinator port (a relaunch must not fight TIME_WAIT for
  the previous rendezvous port);
- exposes process 0's ``/metrics`` sidecar on ``--metrics_port``
  (``CHILD_METRICS_PORT``), so the supervisor scrapes the REAL fleet skew
  gauges — ``train_boundary_skew_seconds`` / ``train_boundary_straggler``
  / ``train_process_count`` from the gloo allgather, not a simulation;
- arms the existing ``FLEET_STRAGGLER_MS`` hook (one process delays every
  boundary allgather) behind a one-shot ``--straggler_marker``, written at
  launch while arming — the supervisor's RELAUNCH of this same command
  runs clean, the rebalanced-away shape;
- RELAYS SIGTERM to the workers: the supervisor's graceful mitigation
  preempt reaches every process's preemption machinery, the fleet takes
  the collective preempt decision at a flush boundary, emergency-saves,
  and every worker exits 75 — which this launcher then exits with, so the
  supervisor sees the clean preempt its contract promises;
- accepts the supervisor's appended ``--resume <run_dir>`` and forwards it
  to every worker;
- writes ``<workdir>/fleet_result.json`` on a completed run: per-process
  final step/digest (the bit-identity evidence input) plus the
  ``FLEET_SHARE_HINT`` it was launched under — proof the rebalance hint
  actually carried into the relaunched fleet's environment.

Exit code: 75 when any worker was preempted (collective preempt means all
of them were), 0 when all completed, else the first failure's code
(negative signal deaths shell-normalized to 128+N).
"""

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "multiprocess_child.py")

_terminate = {"flag": False}


def _handle_term(signum, frame):  # noqa: ARG001 — handler signature
    _terminate["flag"] = True


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser("supervised gloo fleet launcher")
    p.add_argument("--workdir", required=True)
    p.add_argument("--nproc", type=int, default=2)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--resume", default="",
                   help="forwarded to every worker (the supervisor appends "
                        "this on relaunches; argparse last-wins)")
    p.add_argument("--metrics_port", type=int, default=0,
                   help="process 0's /metrics sidecar (the supervisor's "
                        "scrape target)")
    p.add_argument("--straggler_ms", type=float, default=0.0,
                   help="FLEET_STRAGGLER_MS injection: delay this process's "
                        "arrival at every boundary allgather")
    p.add_argument("--straggler_pid", type=int, default=1,
                   help="which process straggles")
    p.add_argument("--straggler_marker", default="",
                   help="one-shot gate: injection arms only while this "
                        "file is absent (written at launch when arming), "
                        "so the supervisor's relaunch runs clean")
    p.add_argument("--result_json", default="",
                   help="default: <workdir>/fleet_result.json")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)
    result_json = args.result_json or os.path.join(
        args.workdir, "fleet_result.json"
    )

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # workers build their own 1-device topology; the supervisor-managed
    # device-count flag (topology_env) is a per-worker concern a real
    # scheduler realizes — stripping it here mirrors tests/_child_env
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    env["CHILD_LOCAL_DEVICES"] = "1"
    env["CHILD_GUARDED"] = "1"

    armed = args.straggler_ms > 0 and not (
        args.straggler_marker and os.path.exists(args.straggler_marker)
    )
    if armed:
        env["FLEET_STRAGGLER_MS"] = str(args.straggler_ms)
        env["FLEET_STRAGGLER_PID"] = str(args.straggler_pid)
        if args.straggler_marker:
            with open(args.straggler_marker, "w") as f:
                f.write(f"straggler {args.straggler_ms}ms")
        print(
            f"FLEET straggler armed: p{args.straggler_pid} "
            f"+{args.straggler_ms}ms/boundary",
            flush=True,
        )
    else:
        env.pop("FLEET_STRAGGLER_MS", None)

    share_hint = env.get("FLEET_SHARE_HINT", "")
    if share_hint:
        # the rebalance hint the supervisor carried into this relaunch
        # (launch.share_env): on a real fleet the scheduler would route
        # fewer examples to the named host; recorded here as evidence
        print(f"FLEET share hint: {share_hint}", flush=True)

    port = _free_port()
    procs, logs = [], []
    for i in range(args.nproc):
        child_env = dict(env)
        if i == 0 and args.metrics_port:
            child_env["CHILD_METRICS_PORT"] = str(args.metrics_port)
        log_path = os.path.join(args.workdir, f"fleet_p{i}.log")
        logs.append(log_path)
        argv_i = [
            sys.executable, CHILD, str(i), str(args.nproc), str(port),
            "driver", args.workdir, str(args.epochs),
        ]
        if args.resume:
            argv_i.append(args.resume)
        procs.append(
            subprocess.Popen(
                argv_i, env=child_env, cwd=REPO,
                stdout=open(log_path, "w"), stderr=subprocess.STDOUT,
            )
        )
    print(
        f"FLEET launched: {args.nproc} workers, coordinator :{port}, "
        f"pids {[p.pid for p in procs]}",
        flush=True,
    )

    prev = {}
    for s in (signal.SIGTERM, signal.SIGINT):
        prev[s] = signal.signal(s, _handle_term)
    relayed = False
    try:
        while any(p.poll() is None for p in procs):
            if _terminate["flag"] and not relayed:
                relayed = True
                print("FLEET relaying SIGTERM to workers", flush=True)
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
            time.sleep(0.1)
    finally:
        for s, h in prev.items():
            signal.signal(s, h)
        for p in procs:
            if p.poll() is None:  # never orphan a worker
                p.kill()
                p.wait()

    rcs = [p.returncode for p in procs]
    for log_path in logs:
        with open(log_path) as f:
            sys.stdout.write(f.read())
    sys.stdout.flush()

    # per-worker DRIVER lines -> the bit-identity evidence input
    workers = []
    for i, log_path in enumerate(logs):
        entry = {"process": i, "rc": rcs[i]}
        with open(log_path) as f:
            for line in f:
                if line.startswith("DRIVER "):
                    entry["step"] = int(line.split("step=")[1].split()[0])
                    entry["digest"] = float(
                        line.split("digest=")[1].split()[0]
                    )
                    entry["save_folder"] = line.split("save_folder=")[
                        1
                    ].strip()
        workers.append(entry)

    if all(rc == 0 for rc in rcs):
        with open(result_json, "w") as f:
            json.dump(
                {
                    "nproc": args.nproc,
                    "epochs": args.epochs,
                    "resume": args.resume,
                    "share_hint": share_hint,
                    "straggler_armed": armed,
                    "workers": workers,
                },
                f, indent=1,
            )
        print(f"FLEET done: {result_json}", flush=True)
        sys.exit(0)
    if 75 in rcs:
        print("FLEET preempted (exit 75, state saved)", flush=True)
        sys.exit(75)
    bad = next(rc for rc in rcs if rc != 0)
    sys.exit(128 - bad if bad < 0 else bad)


if __name__ == "__main__":
    main()
